"""implicit-f64-promotion: float64 leaking into traced f32 math.

This framework is an f32 shop (every env/model buffer is pinned
``jnp.float32``), but Python's numeric tower and numpy's defaults are
both 64-bit, and the two failure modes are mirror images:

- with ``jax_enable_x64`` OFF (the default), an f64 constant fed into a
  jitted function is silently truncated to f32 at the boundary — the
  spelled precision is a lie;
- with ``jax_enable_x64`` ON (debug sessions, parity harnesses — the
  exact context where numerics are being scrutinized), the same
  constant is honored and PROMOTES the whole downstream expression to
  f64: 2x memory, a different numerical trajectory, and a retrace of
  every consumer whose input dtype just changed — the budget-1
  RetraceGuards turn that into a hard failure.

Flagged inside traced scopes:

1. **Explicit float64 spellings** — ``np.float64(...)`` /
   ``jnp.float64(...)`` / ``np.double(...)`` constructor calls,
   ``dtype=`` arguments naming float64 (``np.float64``, ``"float64"``,
   ``"f8"``, or the builtin ``float``, which numpy reads as f64), and
   ``.astype`` to any of those. These are hazards regardless of taint:
   a trace-time f64 constant poisons whatever traced math later touches
   it.
2. **Host-f64 producers mixed with traced values** — a binary
   expression with a traced operand on one side and, on the other, a
   host numpy constructor that defaults to float64: ``np.array`` /
   ``np.asarray`` / ``np.arange`` / ``np.linspace`` / ``np.full``
   containing a float literal with no ``dtype=``, or ``np.ones`` /
   ``np.zeros`` / ``np.empty`` with no ``dtype=`` (always f64). The fix
   is one keyword: ``dtype=np.float32``.

NOT flagged, deliberately: bare Python float literals in traced
arithmetic (``x * 0.5``) — JAX types these WEAKLY, so they adopt the
traced operand's dtype and promote nothing; demanding
``jnp.float32(0.5)`` everywhere would be noise. (The scan-carry case,
where weak literals do bite, is scan-carry-weak-type's beat.)
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

_F64_CTORS = frozenset(
    {
        "np.float64",
        "numpy.float64",
        "np.double",
        "numpy.double",
        "jnp.float64",
        "jax.numpy.float64",
    }
)
_F64_DTYPE_STRINGS = frozenset({"float64", "f8", "<f8", ">f8", "double"})
# numpy constructors whose result dtype defaults to float64: always for
# the shape-taking ones, and whenever a float literal is among the data
# for the value-taking ones.
_ALWAYS_F64_PRODUCERS = frozenset(
    {"np.ones", "numpy.ones", "np.zeros", "numpy.zeros",
     "np.empty", "numpy.empty"}
)
_FLOAT_DATA_F64_PRODUCERS = frozenset(
    {"np.array", "numpy.array", "np.asarray", "numpy.asarray",
     "np.arange", "numpy.arange", "np.linspace", "numpy.linspace",
     "np.full", "numpy.full"}
)


def _names_f64(node: ast.AST) -> bool:
    """Does this expression spell the float64 dtype? (name chain, string
    alias, or the builtin ``float``, which numpy canonicalizes to f64)"""
    name = dotted_name(node)
    if name in _F64_CTORS or name == "float":
        return True
    return (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value in _F64_DTYPE_STRINGS
    )


def _has_float_literal(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Constant) and isinstance(n.value, float)
        for n in ast.walk(node)
    )


def _dtype_keyword(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    return None


def _f64_producer(node: ast.AST) -> Optional[str]:
    """Name of the host numpy call under ``node`` that produces float64
    by default (no ``dtype=`` and, for the value-taking constructors, a
    float literal in the data), else None."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        fname = dotted_name(sub.func)
        if not fname or _dtype_keyword(sub) is not None:
            continue
        if fname in _ALWAYS_F64_PRODUCERS:
            return fname
        if fname in _FLOAT_DATA_F64_PRODUCERS and any(
            _has_float_literal(a) for a in sub.args
        ):
            return fname
    return None


class ImplicitF64Promotion(Rule):
    name = "implicit-f64-promotion"
    default_severity = "error"
    description = (
        "float64 reaching traced f32 math under jit — silently truncated "
        "with x64 off, a promotion + retrace with x64 on; pin dtype=float32"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for root in ctx.traced_roots:
            taint = ctx.taint_for(root)
            seen: Set[int] = set()  # one report per offending node
            for node in ast.walk(root):
                hit = None
                if isinstance(node, ast.Call):
                    hit = self._explicit_f64(node)
                elif isinstance(node, ast.BinOp):
                    hit = self._mixed_producer(ctx, node, taint)
                if hit and id(node) not in seen:
                    seen.add(id(node))
                    yield (node.lineno, node.col_offset, hit)

    @staticmethod
    def _explicit_f64(node: ast.Call) -> Optional[str]:
        fname = dotted_name(node.func)
        if fname in _F64_CTORS:
            return (
                f"{fname}(...) builds a float64 scalar inside a traced "
                "scope — truncated with x64 off, promotes the traced "
                "math (and retraces consumers) with x64 on; use "
                "jnp.float32"
            )
        dtype = _dtype_keyword(node)
        if dtype is not None and _names_f64(dtype):
            return (
                f"dtype={ast.unparse(dtype)} requests float64 inside a "
                "traced scope — pin jnp.float32 (the builtin `float` "
                "dtype means f64 to numpy)"
            )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args
            and _names_f64(node.args[0])
        ):
            return (
                f".astype({ast.unparse(node.args[0])}) casts to float64 "
                "inside a traced scope — truncated with x64 off, a "
                "promotion + retrace with x64 on"
            )
        return None

    @staticmethod
    def _mixed_producer(
        ctx: ModuleContext, node: ast.BinOp, taint
    ) -> Optional[str]:
        for tainted_side, other in (
            (node.left, node.right),
            (node.right, node.left),
        ):
            if not ctx.expr_tainted(tainted_side, taint):
                continue
            if ctx.expr_tainted(other, taint):
                continue  # both traced: dtypes already pinned upstream
            producer = _f64_producer(other)
            if producer:
                return (
                    f"{producer}(...) defaults to float64 and is mixed "
                    "with a traced value — under jax_enable_x64 this "
                    "promotes the whole expression (and retraces "
                    "consumers); pass dtype=np.float32"
                )
        return None
