"""span-in-traced-scope: host-side tracing smuggled into compiled code.

The obs tracing spine (``marl_distributedformation_tpu/obs/``) is
host-only by contract: spans and events are recorded at dispatch seams
(scheduler, reload commit, gate eval), never inside the program being
dispatched. A ``tracer.span(...)`` / ``tracer.event(...)`` call inside
a jit/vmap/scan traced scope is doubly wrong: at best it records
trace-time (compile-time) garbage that silently measures nothing; at
worst the recorded value is a tracer object and the ring fills with
unreadable reprs — and either way host work has leaked into what must
stay a pure compiled program. This rule rejects it statically, which is
what lets every instrumented hot path keep its budget-1 compile receipt
with tracing enabled: the spine is graftlint-clean by construction.

Detection surfaces (mirroring how the spine is actually called):

- method calls whose receiver chain names a tracer — ``tracer.span``,
  ``self._tracer.event``, ``obs.get_tracer().incident`` — with the
  method in the recording set;
- names imported from an ``obs``/``tracer`` module and called directly
  (``from ...obs import span``-style helpers, should any appear);
- one same-module call hop, like rule 12: a traced scope calling a
  local helper whose body records is the same hazard wearing a
  function name.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

# Recording entry points on a Tracer (obs/tracer.py). incident() dumps
# the flight recorder — file IO under trace is the worst of the bunch.
_RECORD_METHODS = frozenset({"span", "event", "add_span", "incident"})
# Module-path fragments that mark an import as the tracing spine.
_OBS_MODULE_PARTS = frozenset({"obs", "tracer"})


def _is_obs_module(module: str) -> bool:
    return any(part in _OBS_MODULE_PARTS for part in module.split("."))


class SpanInTracedScope(Rule):
    name = "span-in-traced-scope"
    default_severity = "error"
    description = (
        "obs.Tracer span/event recording reachable inside a jit/scan/"
        "vmap traced scope — host work smuggled into the compiled "
        "program; record at the dispatch seam instead"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        obs_names = self._obs_imports(ctx.tree)
        reported: Set[Tuple[int, int]] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_traced_scope(node) is None:
                continue
            hit = self._record_call(ctx, node, obs_names)
            if hit and (node.lineno, node.col_offset) not in reported:
                reported.add((node.lineno, node.col_offset))
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{hit} inside a traced scope records at trace time "
                    "(or worse, per compiled iteration) — tracing is "
                    "host-side only; move the span to the dispatch seam "
                    "around the jitted call",
                )

    # -- import surface ---------------------------------------------------

    @staticmethod
    def _obs_imports(tree: ast.Module) -> Set[str]:
        """Local names bound from obs/tracer modules: both
        ``from ...obs import get_tracer`` targets and ``import ...obs
        as o`` aliases."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if _is_obs_module(node.module or ""):
                    for alias in node.names:
                        if alias.name != "*":
                            names.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if _is_obs_module(alias.name):
                        names.add(alias.asname or alias.name.split(".")[0])
        return names

    # -- call classification ----------------------------------------------

    def _record_call(
        self, ctx: ModuleContext, node: ast.Call, obs_names: Set[str]
    ) -> Optional[str]:
        """A human-readable description when this call records to the
        tracing spine (directly or one same-module hop away); else None."""
        direct = self._direct_record(node, obs_names)
        if direct:
            return direct
        # One call hop: a traced scope calling a same-module helper that
        # records (rule 12's reachability idiom).
        if isinstance(node.func, ast.Name):
            for definition in ctx._defs_by_name.get(node.func.id, ()):
                for inner in ast.walk(definition):
                    if isinstance(inner, ast.Call):
                        hit = self._direct_record(inner, obs_names)
                        if hit:
                            return (
                                f"{node.func.id}() reaches {hit}"
                            )
        return None

    def _direct_record(
        self, node: ast.Call, obs_names: Set[str]
    ) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr not in _RECORD_METHODS:
                return None
            receiver = func.value
            # get_tracer().span(...) / obs.get_tracer().event(...)
            if isinstance(receiver, ast.Call):
                rname = dotted_name(receiver.func) or ""
                if rname.split(".")[-1] == "get_tracer" or (
                    rname.split(".")[0] in obs_names
                ):
                    return f"{rname}().{func.attr}(...)"
                return None
            rname = dotted_name(receiver)
            if rname is None:
                return None
            parts = rname.split(".")
            if any("tracer" in p.lower() for p in parts) or (
                parts[0] in obs_names
            ):
                return f"{rname}.{func.attr}(...)"
            return None
        if isinstance(func, ast.Name):
            if func.id in obs_names and func.id in _RECORD_METHODS:
                return f"{func.id}(...)"
        return None
