"""missing-donate: train-step-shaped jits that never donate their buffers.

A train step consumes its previous state and returns the next one; jit
without ``donate_argnums`` keeps both alive across the dispatch, doubling
live HBM for the largest buffers in the program (params + optimizer
moments + env state). The rule is deliberately NARROW: it fires only on
jit targets whose name says train-step (``train_step`` / ``update_step``
/ ``*iteration*``), and an assignment target containing ``no_donate``
documents the exception (timing twins, reusable-input evaluators) and is
skipped. Plain env steps and eval functions never match — their inputs
are legitimately reused.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    JIT_NAMES,
    ModuleContext,
    Rule,
    dotted_name,
)

_TRAIN_SHAPED = re.compile(r"(train_step|update_step|iteration)")
_DONATE_KWARGS = frozenset({"donate_argnums", "donate_argnames"})


def _callable_name(node: ast.AST) -> Optional[str]:
    """Last-segment name of the jitted target, peeling wrapping calls
    (``jax.jit(_burst(iteration, r))`` -> ``_burst`` peels to its first
    arg ``iteration``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call) and node.args:
        inner = _callable_name(node.args[0])
        if inner is not None:
            return inner
        return _callable_name(node.func)
    return None


class MissingDonate(Rule):
    name = "missing-donate"
    default_severity = "error"
    description = (
        "jit of a train-step-shaped function without donate_argnums — "
        "doubles live HBM for the biggest buffers in the program"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in JIT_NAMES or not node.args:
                continue
            target = _callable_name(node.args[0])
            if target is None or not _TRAIN_SHAPED.search(target):
                continue
            if any(kw.arg in _DONATE_KWARGS for kw in node.keywords):
                continue
            if self._assignment_opts_out(ctx, node):
                continue
            yield (
                node.lineno,
                node.col_offset,
                f"jax.jit({target}, ...) looks like a train step but "
                "passes no donate_argnums — the previous state stays "
                "live across the dispatch (name the binding *_no_donate "
                "if the non-donating twin is intentional)",
            )

    @staticmethod
    def _assignment_opts_out(ctx: ModuleContext, node: ast.Call) -> bool:
        """``x_no_donate = jax.jit(...)`` documents a deliberate
        non-donating twin (e.g. profiling reruns on the same buffers)."""
        cur = ctx.parents.get(node)
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = ctx.parents.get(cur)
        if isinstance(cur, ast.Assign):
            for t in cur.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and "no_donate" in n.id:
                        return True
                    if isinstance(n, ast.Attribute) and "no_donate" in n.attr:
                        return True
        return False
