"""fault-point-in-traced-scope: chaos injection smuggled into compiled
code.

The chaos plane (``marl_distributedformation_tpu/chaos/plane.py``) is
host-only by the same contract as the Tracer (rule 15) and the
MetricsRegistry (rule 18): injection points live at dispatch seams —
the checkpoint write, the scheduler's worker loop, the gate's eval
body — never inside the program being dispatched. A
``fault_point(...)`` / ``plane.hit(...)`` call inside a jit/vmap/scan
traced scope is doubly wrong: at best it counts one hit at TRACE time
(the armed fault fires once per COMPILE while the campaign believes it
is exercising every step); at worst the injected exception unwinds a
tracer mid-trace and the "failure" being tested is an artifact of the
test rig. Rejecting it statically is what lets every seam keep its
budget-1 compile receipt with chaos armed — the plane can be wired
into production paths unconditionally because it provably never enters
them compiled.

Detection surfaces (rule 15/18's reachability analysis extended to the
chaos API):

- bare calls to names imported from a ``chaos``/``plane`` module —
  ``fault_point(...)`` after ``from ...chaos import fault_point``;
- method calls whose receiver chain names the plane —
  ``get_fault_plane().hit(...)``, ``plane.hit(...)``,
  ``self._fault_plane.hit(...)`` — with the method in the recording
  set (``hit``, or the arming set ``arm``: arming at trace time is the
  same hazard one call earlier);
- one same-module call hop, like rules 12/15/18: a traced scope
  calling a local helper whose body injects is the same hazard wearing
  a function name.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

# Injection entry points on a FaultPlane handle (chaos/plane.py).
_RECORD_METHODS = frozenset({"hit", "arm"})
# Module-level helpers callable bare after a chaos import.
_BARE_CALLS = frozenset({"fault_point"})
# Module-path fragments that mark an import as the chaos plane.
_CHAOS_MODULE_PARTS = frozenset({"chaos"})


def _is_chaos_module(module: str) -> bool:
    return any(part in _CHAOS_MODULE_PARTS for part in module.split("."))


class FaultPointInTracedScope(Rule):
    name = "fault-point-in-traced-scope"
    default_severity = "error"
    description = (
        "chaos.fault_point / FaultPlane.hit reachable inside a jit/scan/"
        "vmap traced scope — injection counts hits at trace time (once "
        "per COMPILE, not per step) and an injected fault would unwind "
        "the tracer itself; inject at the dispatch seam instead"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        chaos_names = self._chaos_imports(ctx.tree)
        reported: Set[Tuple[int, int]] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_traced_scope(node) is None:
                continue
            hit = self._record_call(ctx, node, chaos_names)
            if hit and (node.lineno, node.col_offset) not in reported:
                reported.add((node.lineno, node.col_offset))
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{hit} inside a traced scope injects at trace time "
                    "(once per COMPILE, not per step) — the chaos plane "
                    "is host-side only; put the injection point at the "
                    "dispatch seam around the jitted call",
                )

    # -- import surface ---------------------------------------------------

    @staticmethod
    def _chaos_imports(tree: ast.Module) -> Set[str]:
        """Local names bound from chaos modules: both
        ``from ...chaos import fault_point`` targets and
        ``import ...chaos as c`` aliases."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if _is_chaos_module(node.module or ""):
                    for alias in node.names:
                        if alias.name != "*":
                            names.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if _is_chaos_module(alias.name):
                        names.add(alias.asname or alias.name.split(".")[0])
        return names

    # -- call classification ----------------------------------------------

    def _record_call(
        self, ctx: ModuleContext, node: ast.Call, chaos_names: Set[str]
    ) -> Optional[str]:
        """A human-readable description when this call reaches the
        chaos plane (directly or one same-module hop away); else
        None."""
        direct = self._direct_record(node, chaos_names)
        if direct:
            return direct
        # One call hop: a traced scope calling a same-module helper that
        # injects (rule 12/15/18's reachability idiom).
        if isinstance(node.func, ast.Name):
            for definition in ctx._defs_by_name.get(node.func.id, ()):
                for inner in ast.walk(definition):
                    if isinstance(inner, ast.Call):
                        hit = self._direct_record(inner, chaos_names)
                        if hit:
                            return f"{node.func.id}() reaches {hit}"
        return None

    def _direct_record(
        self, node: ast.Call, chaos_names: Set[str]
    ) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name):
            # fault_point(...) bare, or any chaos-imported name called
            # through directly.
            if func.id in _BARE_CALLS or func.id in chaos_names:
                return f"{func.id}(...)"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        if func.attr not in _RECORD_METHODS:
            # chaos.fault_point(...) via a module alias.
            if func.attr in _BARE_CALLS:
                rname = dotted_name(func.value)
                if rname and rname.split(".")[0] in chaos_names:
                    return f"{rname}.{func.attr}(...)"
            return None
        if self._plane_like(func.value, chaos_names):
            rname = dotted_name(func.value)
            if rname is None and isinstance(func.value, ast.Call):
                inner = dotted_name(func.value.func)
                rname = f"{inner}()" if inner else "<plane>()"
            return f"{rname or '<plane>'}.{func.attr}(...)"
        return None

    @staticmethod
    def _plane_like(expr: ast.AST, chaos_names: Set[str]) -> bool:
        """Does this receiver expression denote the fault plane?
        Receiver chains must look plane-like (``plane``/``fault`` in a
        part, ``get_fault_plane()`` as the root, or a root bound from a
        chaos import) before the method-name check applies —
        ``schedule.hit`` on an unrelated object stays clean."""
        if isinstance(expr, ast.Call):
            fname = dotted_name(expr.func) or ""
            if fname:
                parts = fname.split(".")
                if (
                    parts[-1] == "get_fault_plane"
                    or parts[0] in chaos_names
                ):
                    return True
            return False
        rname = dotted_name(expr)
        if rname is None:
            return False
        parts = rname.split(".")
        return (
            any(
                "plane" in p.lower() or "fault" in p.lower() for p in parts
            )
            or parts[0] in chaos_names
        )
