"""env-contract-impurity: host impurity inside an env step/reset.

The ``envs/`` contract (docs/environments.md) requires ``reset`` /
``step`` / ``reset_batch`` / ``step_batch`` to be pure pytree->pytree
functions: all randomness flows through the explicit JAX key threaded in
the state, and nothing closes over mutable trace-time host state. An env
that draws from the HOST RNG (``np.random.*`` / stdlib ``random.*``)
traces the draw ONCE and bakes the sample into the compiled program —
every subsequent call replays the same "random" value, which trains and
evals without error on silently degenerate data. A ``global`` statement
in a step is the same bug from the other side: the rebind happens at
trace time only, so the compiled steps disagree with the host's idea of
the state.

Detection is name-scoped: functions named exactly ``step`` / ``reset`` /
``step_batch`` / ``reset_batch`` (the registered-contract field names,
``envs/spec.py``) and every function nested inside one. Host RNG aliases
are resolved from the module's imports, so ``from jax import random``
never collides with stdlib ``random``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    FunctionLike,
    ModuleContext,
    Rule,
    dotted_name,
)

# The registered-env contract surface (EnvSpec field names, envs/spec.py).
_ENV_FN_NAMES = frozenset({"step", "reset", "step_batch", "reset_batch"})


def _host_rng_aliases(tree: ast.Module) -> Set[str]:
    """Dotted prefixes denoting the HOST RNG in this module: stdlib
    ``random`` and ``numpy.random`` under whatever names they were
    imported as. Keyed on actual imports, so ``from jax import random``
    (the JAX module) is never mistaken for the stdlib."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "random":
                    aliases.add(a.asname or "random")
                elif a.name == "numpy":
                    aliases.add(f"{a.asname or 'numpy'}.random")
                elif a.name == "numpy.random":
                    aliases.add(a.asname or "numpy.random")
        elif isinstance(node, ast.ImportFrom) and node.module == "numpy":
            for a in node.names:
                if a.name == "random":
                    aliases.add(a.asname or "random")
    return aliases


class EnvContractImpurity(Rule):
    name = "env-contract-impurity"
    default_severity = "error"
    description = (
        "an env step/reset draws from the host RNG or rebinds a global — "
        "the draw is baked in at trace time; thread a JAX key instead"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        aliases = _host_rng_aliases(ctx.tree)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name not in _ENV_FN_NAMES:
                continue
            # The whole subtree: closures (scan bodies, vmapped helpers)
            # trace with the env function they are defined in.
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"env function {fn.name!r} rebinds global(s) "
                        f"{', '.join(node.names)} — mutable host state "
                        "does not survive tracing; carry it in the env "
                        "state pytree",
                    )
                elif isinstance(node, ast.Call):
                    fname = dotted_name(node.func) or ""
                    head = fname.rpartition(".")[0]
                    if head in aliases:
                        yield (
                            node.lineno,
                            node.col_offset,
                            f"env function {fn.name!r} calls host RNG "
                            f"{fname}() — the sample is baked into the "
                            "compiled step; use jax.random with the "
                            "key threaded through the state",
                        )


__all__ = ["EnvContractImpurity"]
