"""print-in-jit: host printing / tracer interpolation inside traced code.

``print`` inside a jitted function runs at trace time only — it shows
the TRACER once per compile, never the runtime values, and its absence
on later calls is routinely misread as "the code stopped running".
Interpolating a traced value into an f-string is the same bug in string
clothing: the formatted text bakes in ``Traced<ShapedArray(...)>``.
``jax.debug.print`` is the supported spelling for both. F-strings over
static values (shapes in error messages) are idiomatic and stay allowed.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from marl_distributedformation_tpu.analysis.linter import ModuleContext, Rule


class PrintInJit(Rule):
    name = "print-in-jit"
    default_severity = "error"
    description = (
        "print / f-string on traced values inside a jitted function — "
        "runs at trace time with tracer reprs; use jax.debug.print"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for root in ctx.traced_roots:
            taint = ctx.taint_for(root)
            for node in ast.walk(root):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                ):
                    yield (
                        node.lineno,
                        node.col_offset,
                        "print() inside a jitted function runs at trace "
                        "time only — use jax.debug.print for runtime "
                        "values (or drop it)",
                    )
                elif (
                    isinstance(node, ast.JoinedStr)
                    and not self._in_failure_path(ctx, node)
                    and any(
                        isinstance(v, ast.FormattedValue)
                        and ctx.expr_tainted(v.value, taint)
                        for v in node.values
                    )
                ):
                    yield (
                        node.lineno,
                        node.col_offset,
                        "f-string interpolates a traced value — the text "
                        "bakes in the tracer repr; use jax.debug.print "
                        "formatting instead",
                    )

    @staticmethod
    def _in_failure_path(ctx: ModuleContext, node: ast.AST) -> bool:
        """F-strings in ``assert`` / ``raise`` messages only evaluate on
        the trace-time failure path — a tracer repr there is a debugging
        aid, not a landmine."""
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = ctx.parents.get(cur)
        return isinstance(cur, (ast.Assert, ast.Raise))
