"""mutable-capture-in-jit: trace-time mutable state in jitted closures.

A mutable default argument (``def step(x, buf=[])``) or a ``global``
write inside a jitted function executes at *trace* time, not run time:
the side effect happens once per compile (silently skipped on cache
hits, repeated on retraces) and never per step — the classic "my counter
only advanced twice" bug. Flag both; trace-time reads of module globals
(constants, config) are idiomatic and stay allowed.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from marl_distributedformation_tpu.analysis.linter import ModuleContext, Rule

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
_MUTABLE_CTORS = frozenset({"list", "dict", "set", "bytearray", "collections.deque", "deque"})


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, _MUTABLE_LITERALS):
        return True
    if isinstance(node, ast.Call):
        from marl_distributedformation_tpu.analysis.linter import dotted_name

        return dotted_name(node.func) in _MUTABLE_CTORS
    return False


class MutableCaptureInJit(Rule):
    name = "mutable-capture-in-jit"
    default_severity = "error"
    description = (
        "mutable default argument or global/nonlocal write in a jitted "
        "function — the side effect runs at trace time, not per step"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for root in ctx.traced_roots:
            for node in ast.walk(root):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defaults = [
                        *node.args.defaults, *node.args.kw_defaults,
                    ]
                    for d in defaults:
                        if d is not None and _is_mutable_default(d):
                            yield (
                                d.lineno,
                                d.col_offset,
                                f"mutable default argument on jitted "
                                f"function {node.name!r} — shared across "
                                "every trace; pass it explicitly",
                            )
                elif isinstance(node, (ast.Global, ast.Nonlocal)):
                    names = ", ".join(node.names)
                    kind = (
                        "global" if isinstance(node, ast.Global) else "nonlocal"
                    )
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"`{kind} {names}` write inside a jitted function "
                        "runs at trace time only (once per compile, never "
                        "per step) — thread state through the function "
                        "instead",
                    )
