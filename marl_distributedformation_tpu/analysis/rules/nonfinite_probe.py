"""host-nonfinite-probe-in-dispatch-loop: per-iteration divergence
polling that forces a device sync.

The tempting way to watch a training loop for NaNs is to probe every
dispatch from the host::

    while steps < total:
        metrics = jitted_step(...)
        if jnp.isnan(metrics["loss"]).any():   # <- full device sync
            break

Every such probe blocks the host on the device value — on a tunneled
TPU that is a full RTT per iteration, and under fused dispatch it
defeats the entire point of the scan (the host re-synchronizes per
chunk member). It is also K iterations TOO LATE: with ``fused_chunk=K``
the damage is committed before the host can see it. The repo's answer
is the in-program health word (train/recovery.py): finiteness is
computed ON DEVICE inside the compiled step, rides the stacked chunk
metrics through the ONE batched drain the loop already pays for, and
the ``jnp.where`` skip-update guard contains the poisoned iteration
without any host round trip. This rule statically rejects the
anti-pattern the health word exists to replace.

Detection, inside a host-side ``while``/``for`` loop body (loops in
traced scopes are rule 2's report; the serving/training dispatch loops
this rule polices are host loops):

- ``jnp.isnan`` / ``jnp.isinf`` / ``jnp.isfinite`` calls (any
  ``jnp``/``jax.numpy`` spelling, or the names from-imported from
  ``jax.numpy``) — applying them to a host value is itself the smell
  (that is numpy's job), and applying them to a device value is the
  sync;
- ``math.isnan(float(x))`` / ``np.isfinite(float(x))`` style probes —
  the ``float()`` call IS the forced transfer, the finiteness wrapper
  marks it as a divergence poll;
- a plain-name call into a helper chain that probes, followed on the
  shared call graph (``analysis/callgraph.py``) to its depth bound.
  Callees that are themselves traced scopes are pruned: a traced
  helper's ``jnp.isnan`` is the in-program health word — the sanctioned
  replacement, not the hazard.

What stays CLEAN, deliberately: ``np.isfinite`` over already-drained
numpy arrays (the drain seam's legitimate batched check), ``float(v)``
on drained host metrics (the trainer's log path), and any probe
OUTSIDE a loop (a one-shot end-of-run finiteness check is exactly how
the trainer guarantees finite final params).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis import callgraph
from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

# Finiteness predicates. The jnp spellings are probes wherever they
# appear in a host loop; the host-math spellings only when their
# argument is a float(...) extraction (numpy over host data is fine).
_PROBE_ATTRS = frozenset({"isnan", "isinf", "isfinite"})
_JNP_ROOTS = frozenset({"jnp", "jax.numpy"})
_HOST_ROOTS = frozenset({"math", "np", "numpy"})


def _jnp_probe_name(fname: Optional[str]) -> bool:
    if not fname or "." not in fname:
        return False
    root, attr = fname.rsplit(".", 1)
    return attr in _PROBE_ATTRS and root in _JNP_ROOTS


def _host_probe_name(fname: Optional[str]) -> bool:
    if not fname or "." not in fname:
        return False
    root, attr = fname.rsplit(".", 1)
    return attr in _PROBE_ATTRS and root in _HOST_ROOTS


def _probe_pred(node: ast.Call, fname) -> "str | None":
    """Call-graph predicate: is this call site a host finiteness probe?
    (jnp spellings anywhere; math/np spellings only over a float()/
    .item() pull — see the module docstring.)"""
    if _jnp_probe_name(fname):
        return f"{fname}(...)"
    if _host_probe_name(fname) and _has_float_extraction(node):
        return f"{fname}(float(...))"
    return None


def _has_float_extraction(node: ast.Call) -> bool:
    """Does any argument contain a ``float(...)``/``.item()`` pull —
    the forced device->host transfer that turns a host-math finiteness
    check into a per-iteration sync?"""
    for arg in ast.walk(node):
        if isinstance(arg, ast.Call):
            if isinstance(arg.func, ast.Name) and arg.func.id == "float":
                return True
            if (
                isinstance(arg.func, ast.Attribute)
                and arg.func.attr == "item"
            ):
                return True
    return False


class HostNonfiniteProbeInDispatchLoop(Rule):
    name = "host-nonfinite-probe-in-dispatch-loop"
    default_severity = "error"
    description = (
        "host-side jnp.isnan/isinf/isfinite (or math/np probes over a "
        "float() pull) inside a while/for dispatch loop — one device "
        "sync per iteration, and K iterations too late under fused "
        "dispatch; compute the health word in-program instead "
        "(train/recovery.py)"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        jnp_imports = self._jnp_probe_imports(ctx.tree)
        reported: Set[Tuple[int, int]] = set()
        for loop in self._host_loops(ctx):
            for hit in self._scan_body(ctx, loop, jnp_imports):
                if hit[:2] not in reported:
                    reported.add(hit[:2])
                    yield hit

    @staticmethod
    def _host_loops(ctx: ModuleContext) -> List[ast.AST]:
        """Every while/for loop outside traced scopes (a traced loop is
        rule 2's business). Nested loops each appear; the reported set
        keeps one report per call site."""
        return [
            node
            for node in ast.walk(ctx.tree)
            if isinstance(node, (ast.While, ast.For))
            and not ctx._has_traced_ancestor(node)
        ]

    @staticmethod
    def _jnp_probe_imports(tree: ast.Module) -> Set[str]:
        """Local names bound from ``jax.numpy`` that ARE finiteness
        predicates (``from jax.numpy import isnan``)."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and (
                (node.module or "") in ("jax.numpy", "jnp")
            ):
                for alias in node.names:
                    if alias.name in _PROBE_ATTRS:
                        names.add(alias.asname or alias.name)
        return names

    def _scan_body(
        self, ctx: ModuleContext, loop: ast.AST, jnp_imports: Set[str]
    ) -> Iterator[Tuple[int, int, str]]:
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_traced_scope(node) is not None:
                continue  # a jitted helper defined inside the loop
            hit = self._probe_call(ctx, node, jnp_imports)
            if hit:
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{hit} inside a dispatch loop forces one device "
                    "sync per iteration (and sees fused divergence K "
                    "iterations late) — compute the health word "
                    "in-program and consume it at the chunk drain "
                    "(train/recovery.py, docs/recovery.md)",
                )

    def _probe_call(
        self, ctx: ModuleContext, node: ast.Call, jnp_imports: Set[str]
    ) -> Optional[str]:
        fname = dotted_name(node.func)
        if _jnp_probe_name(fname):
            return f"{fname}(...)"
        if fname in jnp_imports:
            return f"{fname}(...) (from jax.numpy)"
        if _host_probe_name(fname) and _has_float_extraction(node):
            return f"{fname}(float(...))"
        # Transitive plain-name chains on the shared call graph; traced
        # callees are pruned — their probes are the in-program health
        # word, i.e. the fix, not the hazard.
        if isinstance(node.func, ast.Name):
            hit = callgraph.reachable_call(
                ctx,
                node,
                _probe_pred,
                first_hops=frozenset({"local", "import"}),
                prune=lambda f: callgraph.traced_in_own_module(f, ctx),
            )
            if hit is not None:
                return f"{node.func.id}() reaches {hit.matched}"
        return None
