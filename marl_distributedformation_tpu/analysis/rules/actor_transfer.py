"""blocking-transfer-in-actor-loop: a sync on the acting critical path.

The sebulba split (train/sebulba/, docs/sebulba.md) only pays off while
the actor lane stays a pure dispatch pipeline: snapshot params, launch
the compiled rollout, enqueue the trajectory, repeat. jax keeps that
pipeline deep by dispatching asynchronously — which a single synchronous
transfer collapses::

    while not stop:                       # the actor loop
        batch = rollout(params, state)
        jax.block_until_ready(batch)      # <- actor idles out the device
        queue.put(jax.device_get(batch))  # <- full device->host round trip

``block_until_ready`` stalls the lane until the device drains (the
learner's backpressure already paces the actor — a second, synchronous
pacing point just serializes the two slices), ``device_get`` drags the
trajectory through host memory that the learner slice would have
received device-to-device, and a bare host ``device_put`` re-uploads
per iteration what the queue's enqueue seam places once per batch
(``train/sebulba/queues.py`` — the sanctioned home, deliberately
OUTSIDE its backpressure loop). The fix is always the seam: hand the
device tree to the ``TransferQueue`` and let its enqueue-time
``device_put`` overlap the next rollout; drain metrics at the learner's
amortized chunk boundary, never in the acting loop.

Scope, deliberately narrow: host-side ``while``/``for`` loops (traced
loops are rule 2's report) whose enclosing function or class name
contains ``actor`` or ``transfer`` — the naming convention of every
acting/transfer lane in this repo. Flagged inside such a loop body:

- ``jax.device_get`` / ``jax.device_put`` / ``jax.block_until_ready``
  dotted calls (or their from-imported plain names);
- ``x.block_until_ready()`` method spellings (the call IS the sync,
  whatever the receiver);
- a plain-name call into a SAME-MODULE helper that makes one of those
  calls — one hop on the shared call graph (``first_hops={"local"}``,
  rules 12/16 precedent). Method calls are not followed: the
  TransferQueue/ParamBus seams are methods invoked from actor loops,
  and following them would flag exactly the off-critical-path homes
  this rule exists to steer toward.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis import callgraph
from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

_BLOCKING_CALLS = frozenset(
    {
        "jax.device_get",
        "device_get",
        "jax.device_put",
        "device_put",
        "jax.block_until_ready",
        "block_until_ready",
    }
)
_SCOPE_MARKERS = ("actor", "transfer")
_NAME_HOPS = frozenset({"local"})


def _blocking_pred(node: ast.Call, fname) -> Optional[str]:
    if fname in _BLOCKING_CALLS:
        return fname
    if isinstance(node.func, ast.Attribute) and (
        node.func.attr == "block_until_ready"
    ):
        return ".block_until_ready"
    return None


class BlockingTransferInActorLoop(Rule):
    name = "blocking-transfer-in-actor-loop"
    default_severity = "error"
    description = (
        "synchronous device_get/device_put/block_until_ready inside an "
        "actor or transfer-queue loop body — a device sync per rollout "
        "on the acting critical path; hand the device tree to the "
        "transfer-queue seam and keep the lane asynchronous"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        reported: Set[Tuple[int, int]] = set()
        for loop in self._actor_loops(ctx):
            for hit in self._scan_body(ctx, loop):
                if hit[:2] not in reported:
                    reported.add(hit[:2])
                    yield hit

    def _actor_loops(self, ctx: ModuleContext) -> List[ast.AST]:
        """Host while/for loops whose enclosing function or class name
        marks an acting/transfer lane. Nested loops each appear; the
        reported set keeps one report per call site."""
        return [
            node
            for node in ast.walk(ctx.tree)
            if isinstance(node, (ast.While, ast.For))
            and not ctx._has_traced_ancestor(node)
            and self._in_actor_scope(ctx, node)
        ]

    @staticmethod
    def _in_actor_scope(ctx: ModuleContext, loop: ast.AST) -> bool:
        for anc in ctx._ancestors(loop):
            if isinstance(
                anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                name = anc.name.lower()
                if any(marker in name for marker in _SCOPE_MARKERS):
                    return True
        return False

    def _scan_body(
        self, ctx: ModuleContext, loop: ast.AST
    ) -> Iterator[Tuple[int, int, str]]:
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_traced_scope(node) is not None:
                continue  # a jitted helper defined inside the loop
            fname = dotted_name(node.func)
            direct = _blocking_pred(node, fname)
            if direct is not None:
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{direct}(...) inside an actor/transfer loop "
                    "synchronizes the acting lane every iteration — "
                    "enqueue the device tree through the transfer-queue "
                    "seam (its enqueue-time device_put overlaps the next "
                    "rollout) and drain host values at the learner's "
                    "chunk boundary",
                )
            elif isinstance(node.func, ast.Name):
                hit = callgraph.reachable_call(
                    ctx, node, _blocking_pred, first_hops=_NAME_HOPS
                )
                if hit is not None:
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"{node.func.id}() is called from an "
                        f"actor/transfer loop and reaches "
                        f"{hit.matched}(...) — a device sync per "
                        "iteration on the acting critical path; move the "
                        "transfer to the queue's enqueue seam",
                    )
