"""metrics-in-traced-scope: live-metrics recording smuggled into
compiled code.

The MetricsRegistry (``marl_distributedformation_tpu/obs/metrics.py``)
is host-only by the same contract as the Tracer (rule 15): counters,
gauges, and histograms are recorded at dispatch seams — the trainer's
drain, the scheduler's batch boundary, the gate's verdict — never
inside the program being dispatched. A ``registry.counter(...).inc()``
inside a jit/vmap/scan traced scope is doubly wrong: at best it bumps
the counter once at TRACE time (silently measuring nothing while
looking instrumented); at worst the recorded value is a tracer object
and the shard fills with unreadable state — and either way host dict
mutation has leaked into what must stay a pure compiled program. This
rule rejects it statically, which is what lets every instrumented hot
path keep its budget-1 compile receipt with telemetry enabled.

Detection surfaces (mirroring how the registry is actually called —
rule 15's reachability analysis extended to the metrics API):

- record calls whose receiver chain names the registry —
  ``registry.gauge("x").set(v)``, ``self._metrics_registry.counter(...)``,
  ``get_registry().histogram(...).observe(...)`` — with the method in
  the recording set (``inc``/``set``/``observe``/``record_gauges``) or
  the handle-minting set (``counter``/``gauge``/``histogram``: minting
  a handle at trace time is the same hazard one call earlier);
- names imported from an ``obs``/``metrics`` module and called through
  (``from ...obs.metrics import get_registry``);
- one same-module call hop, like rules 12/15: a traced scope calling a
  local helper whose body records is the same hazard wearing a
  function name.

Receiver chains must look registry-like (``registry``/``get_registry``
in a part, or a root bound from the obs/metrics modules) before the
method-name check applies — ``self._stop.set()`` and dict ``.update``
calls stay clean.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

# Recording entry points on a MetricsRegistry handle (obs/metrics.py).
_RECORD_METHODS = frozenset({"inc", "set", "observe", "record_gauges"})
# Handle minting on the registry itself — host dict/shard work too.
_HANDLE_METHODS = frozenset({"counter", "gauge", "histogram"})
# Module-path fragments that mark an import as the metrics plane.
_METRICS_MODULE_PARTS = frozenset({"obs", "metrics"})


def _is_metrics_module(module: str) -> bool:
    return any(part in _METRICS_MODULE_PARTS for part in module.split("."))


class MetricsInTracedScope(Rule):
    name = "metrics-in-traced-scope"
    default_severity = "error"
    description = (
        "obs.MetricsRegistry counter/gauge/histogram recording reachable "
        "inside a jit/scan/vmap traced scope — host work smuggled into "
        "the compiled program; record at the dispatch seam instead"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        metrics_names = self._metrics_imports(ctx.tree)
        reported: Set[Tuple[int, int]] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_traced_scope(node) is None:
                continue
            hit = self._record_call(ctx, node, metrics_names)
            if hit and (node.lineno, node.col_offset) not in reported:
                reported.add((node.lineno, node.col_offset))
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{hit} inside a traced scope records at trace time "
                    "(once per COMPILE, not per step) — metrics are "
                    "host-side only; record at the dispatch seam around "
                    "the jitted call",
                )

    # -- import surface ---------------------------------------------------

    @staticmethod
    def _metrics_imports(tree: ast.Module) -> Set[str]:
        """Local names bound from obs/metrics modules: both
        ``from ...obs.metrics import get_registry`` targets and
        ``import ...obs.metrics as m`` aliases."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if _is_metrics_module(node.module or ""):
                    for alias in node.names:
                        if alias.name != "*":
                            names.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if _is_metrics_module(alias.name):
                        names.add(alias.asname or alias.name.split(".")[0])
        return names

    # -- call classification ----------------------------------------------

    def _record_call(
        self, ctx: ModuleContext, node: ast.Call, metrics_names: Set[str]
    ) -> Optional[str]:
        """A human-readable description when this call records to the
        metrics plane (directly or one same-module hop away); else
        None."""
        direct = self._direct_record(node, metrics_names)
        if direct:
            return direct
        # One call hop: a traced scope calling a same-module helper that
        # records (rule 12/15's reachability idiom).
        if isinstance(node.func, ast.Name):
            for definition in ctx._defs_by_name.get(node.func.id, ()):
                for inner in ast.walk(definition):
                    if isinstance(inner, ast.Call):
                        hit = self._direct_record(inner, metrics_names)
                        if hit:
                            return f"{node.func.id}() reaches {hit}"
        return None

    def _direct_record(
        self, node: ast.Call, metrics_names: Set[str]
    ) -> Optional[str]:
        func = node.func
        if not isinstance(func, ast.Attribute):
            return None
        if (
            func.attr not in _RECORD_METHODS
            and func.attr not in _HANDLE_METHODS
        ):
            return None
        if self._registry_like(func.value, metrics_names):
            rname = dotted_name(func.value)
            if rname is None and isinstance(func.value, ast.Call):
                inner = dotted_name(func.value.func)
                rname = f"{inner}()" if inner else "<registry>()"
            return f"{rname or '<registry>'}.{func.attr}(...)"
        return None

    def _registry_like(
        self, expr: ast.AST, metrics_names: Set[str]
    ) -> bool:
        """Does this receiver expression denote the metrics registry (or
        a handle freshly minted from one)?"""
        if isinstance(expr, ast.Call):
            fname = dotted_name(expr.func) or ""
            if fname:
                parts = fname.split(".")
                # get_registry() / obs.get_registry() / m.get_registry()
                if parts[-1] == "get_registry" or parts[0] in metrics_names:
                    return True
            # registry.counter("x") as a receiver: peel the handle mint.
            if isinstance(expr.func, ast.Attribute) and (
                expr.func.attr in _HANDLE_METHODS
            ):
                return self._registry_like(expr.func.value, metrics_names)
            return False
        rname = dotted_name(expr)
        if rname is None:
            return False
        parts = rname.split(".")
        return (
            any("registry" in p.lower() for p in parts)
            or parts[0] in metrics_names
        )
