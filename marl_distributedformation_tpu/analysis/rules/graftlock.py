"""graftlock rule family: host-concurrency lock discipline (rules 23–26).

The chaos plane proves the threaded host seams RECOVER from injected
faults; these rules statically prove the seams cannot deadlock or race
in the first place. All four replay findings the call-graph engine
(``analysis/callgraph.py``) computed once per package snapshot — the
lock model, annotation grammar (``# graftlock: guarded-by= / holds= /
gate / lock=``), and traversal bounds live there; the rules are lookup
tables keyed on the linted module's path.

- **lock-ordering-cycle** — the may-acquire-while-holding graph (only
  UNTIMED acquisitions create edges; same-name pairs are instance
  iteration, not nesting) contains a cycle: two threads entering from
  different edges deadlock. The report carries the full acquisition
  chain, one edge per site.
- **unguarded-shared-mutation** — an attribute declared
  ``guarded-by=<lock>`` is written (assignment, subscript store, or
  container-mutator call) from a thread-target-reachable function on a
  path that does not hold the guard. Opt-in: only declared attributes
  are checked, so the rule has zero false-positive surface on
  unannotated code.
- **blocking-call-under-dispatch-lock** — ``device_get``, untimed
  ``queue.get()`` / ``acquire()`` / ``wait()``, file IO, HTTP, or
  flight-record incident dumps reachable while a dispatch/batch gate
  (``batch_lock`` by convention, or ``# graftlock: gate``) is held —
  the exact shape that extends a fleet-wide serving pause.
- **lock-released-across-await-seam** — a callback (thread target,
  timer, executor submit, done-callback, handler-table entry) is
  registered while holding a lock the callback re-acquires; if the
  registering thread waits on the callback, or the callback can run
  synchronously, the seam deadlocks.

Suppression policy: a finding that is correct-by-design is suppressed
in place with ``# graftlint: disable=<rule>`` plus a rationale on the
same comment — docs/static_analysis.md documents the policy.
"""

from __future__ import annotations

from typing import Iterator, Tuple

from marl_distributedformation_tpu.analysis import callgraph
from marl_distributedformation_tpu.analysis.linter import ModuleContext, Rule


class _GraftlockRule(Rule):
    """Shared replay shell: findings come from the package graph."""

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        pg = callgraph.ENGINE.package_for(ctx)
        key = callgraph.ENGINE.module_key_for(ctx)
        yield from pg.findings_for(key, self.name)


class LockOrderingCycle(_GraftlockRule):
    name = callgraph.LOCK_ORDERING_CYCLE
    default_severity = "error"
    description = (
        "the may-acquire-while-holding graph has a cycle — threads "
        "entering from different edges deadlock; acquire locks in one "
        "global order or make an edge a timed acquire with an abort path"
    )


class UnguardedSharedMutation(_GraftlockRule):
    name = callgraph.UNGUARDED_SHARED_MUTATION
    default_severity = "error"
    description = (
        "an attribute declared `# graftlock: guarded-by=<lock>` is "
        "written from thread-reachable code on a path that does not "
        "hold its guard"
    )


class BlockingCallUnderDispatchLock(_GraftlockRule):
    name = callgraph.BLOCKING_UNDER_GATE
    default_severity = "error"
    description = (
        "a blocking call (device_get, untimed queue.get/acquire/wait, "
        "file IO, HTTP) is reachable while a dispatch/batch gate is "
        "held — it extends the fleet-wide serving pause"
    )


class LockReleasedAcrossAwaitSeam(_GraftlockRule):
    name = callgraph.CALLBACK_LOCK_SEAM
    default_severity = "error"
    description = (
        "a callback is registered while holding a lock the callback "
        "re-acquires — a deadlock whenever the registration side waits "
        "on (or runs) the callback; register after releasing"
    )
