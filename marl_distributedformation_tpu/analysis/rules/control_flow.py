"""traced-python-control-flow: ``if``/``while`` on traced values.

Python control flow evaluates its condition at *trace* time: on a traced
value it either raises a ConcretizationTypeError (under jit) or — the
silent version — bakes one branch into the compiled program and triggers
a retrace whenever the concrete value flips. The fix is ``jnp.where`` /
``lax.cond`` / ``lax.while_loop``. Static predicates (``x is None``,
``x.shape[0] > 2``, ``isinstance(...)``, closure config flags) are
trace-time Python and stay allowed — see the taint rules in
``linter.ModuleContext``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from marl_distributedformation_tpu.analysis.linter import ModuleContext, Rule

_FIX = {
    ast.If: "jnp.where or lax.cond",
    ast.IfExp: "jnp.where or lax.cond",
    ast.While: "lax.while_loop or lax.fori_loop",
}


class TracedPythonControlFlow(Rule):
    name = "traced-python-control-flow"
    default_severity = "error"
    description = (
        "Python if/while on a traced value inside a jitted function — "
        "concretizes at trace time or silently specializes the program"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for root in ctx.traced_roots:
            taint = ctx.taint_for(root)
            for node in ast.walk(root):
                if not isinstance(node, (ast.If, ast.IfExp, ast.While)):
                    continue
                if ctx.expr_tainted(node.test, taint):
                    kind = (
                        "while" if isinstance(node, ast.While) else "if"
                    )
                    yield (
                        node.test.lineno,
                        node.test.col_offset,
                        f"Python `{kind}` on a traced value — use "
                        f"{_FIX[type(node)]} so the branch stays inside "
                        "the compiled program",
                    )
