"""graftlint rule registry. Rules are stateless between files (any
per-check state is reset inside ``check``), so one shared instance per
rule serves every lint run."""

from typing import List

from marl_distributedformation_tpu.analysis.linter import Rule
from marl_distributedformation_tpu.analysis.rules.actor_transfer import (
    BlockingTransferInActorLoop,
)
from marl_distributedformation_tpu.analysis.rules.callbacks import (
    CallbackInHotLoop,
)
from marl_distributedformation_tpu.analysis.rules.capture import (
    MutableCaptureInJit,
)
from marl_distributedformation_tpu.analysis.rules.control_flow import (
    TracedPythonControlFlow,
)
from marl_distributedformation_tpu.analysis.rules.cross_module import (
    CrossModuleCallback,
)
from marl_distributedformation_tpu.analysis.rules.deprecated import DeprecatedApi
from marl_distributedformation_tpu.analysis.rules.dispatch_transfer import (
    DevicePutInDispatchLoop,
)
from marl_distributedformation_tpu.analysis.rules.donation import MissingDonate
from marl_distributedformation_tpu.analysis.rules.env_contract import (
    EnvContractImpurity,
)
from marl_distributedformation_tpu.analysis.rules.f64_promotion import (
    ImplicitF64Promotion,
)
from marl_distributedformation_tpu.analysis.rules.fault_scope import (
    FaultPointInTracedScope,
)
from marl_distributedformation_tpu.analysis.rules.graftlock import (
    BlockingCallUnderDispatchLock,
    LockOrderingCycle,
    LockReleasedAcrossAwaitSeam,
    UnguardedSharedMutation,
)
from marl_distributedformation_tpu.analysis.rules.host_sync import HostSyncInJit
from marl_distributedformation_tpu.analysis.rules.ledger_scope import (
    LedgerRecordInTracedScope,
)
from marl_distributedformation_tpu.analysis.rules.metrics_scope import (
    MetricsInTracedScope,
)
from marl_distributedformation_tpu.analysis.rules.nonfinite_probe import (
    HostNonfiniteProbeInDispatchLoop,
)
from marl_distributedformation_tpu.analysis.rules.numpy_use import NumpyInJit
from marl_distributedformation_tpu.analysis.rules.printing import PrintInJit
from marl_distributedformation_tpu.analysis.rules.prng import PrngKeyReuse
from marl_distributedformation_tpu.analysis.rules.rpc_scope import (
    RpcInTracedScope,
)
from marl_distributedformation_tpu.analysis.rules.scan_carry import (
    ScanCarryWeakType,
)
from marl_distributedformation_tpu.analysis.rules.search_compare import (
    TracedComparisonInSearch,
)
from marl_distributedformation_tpu.analysis.rules.sharding_drift import (
    ScanCarryShardingDrift,
)
from marl_distributedformation_tpu.analysis.rules.span_scope import (
    SpanInTracedScope,
)
from marl_distributedformation_tpu.analysis.rules.vmap_axes import (
    VmapInAxesArity,
)

RULES = (
    NumpyInJit(),
    TracedPythonControlFlow(),
    PrngKeyReuse(),
    HostSyncInJit(),
    MutableCaptureInJit(),
    DeprecatedApi(),
    MissingDonate(),
    PrintInJit(),
    ScanCarryWeakType(),
    VmapInAxesArity(),
    ImplicitF64Promotion(),
    CallbackInHotLoop(),
    ScanCarryShardingDrift(),
    CrossModuleCallback(),
    SpanInTracedScope(),
    DevicePutInDispatchLoop(),
    TracedComparisonInSearch(),
    MetricsInTracedScope(),
    FaultPointInTracedScope(),
    LedgerRecordInTracedScope(),
    RpcInTracedScope(),
    HostNonfiniteProbeInDispatchLoop(),
    LockOrderingCycle(),
    UnguardedSharedMutation(),
    BlockingCallUnderDispatchLock(),
    LockReleasedAcrossAwaitSeam(),
    BlockingTransferInActorLoop(),
    EnvContractImpurity(),
)


def all_rules() -> List[Rule]:
    return list(RULES)


def rule_names() -> List[str]:
    return [r.name for r in RULES]
