"""scan-carry-weak-type: Python scalar literals as ``lax.scan`` carry
leaves.

A Python ``0`` / ``0.0`` in the scan init is a *weak-typed* scalar.
Inside the loop the carry participates in arithmetic, picks up a strong
dtype, and comes back different from what went in — either an explicit
scan carry-mismatch error, or (the silent version, when the weak leaf
rides through unchanged this trace) a program whose input aval depends
on Python-number promotion rules, where the next call site that passes a
strongly-typed value retraces the whole jitted program. The fix costs
one call: ``jnp.asarray(0.0, jnp.float32)`` (or ``jnp.zeros_like``)
pins the carry dtype at the boundary.

Only literals reachable through plain containers (tuples/lists/dicts and
a unary sign) are flagged: a literal *inside a call* —
``jnp.zeros((3, 4))``, ``jnp.float32(0.0)`` — feeds a constructor that
returns a strong-typed array, which is exactly the fix.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

_SCAN_NAMES = frozenset({"jax.lax.scan", "lax.scan"})
_CONTAINERS = (ast.Tuple, ast.List, ast.Dict, ast.Set)


def _literal_leaves(node: ast.AST) -> Iterator[ast.Constant]:
    """Numeric literals that become carry *leaves* of this init
    expression: the node itself, or literals reached through container
    displays and unary signs. Calls/comprehensions/etc. break the walk —
    their result is whatever the expression constructs."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, (bool, int, float, complex)):
            yield node
        return
    if isinstance(node, ast.UnaryOp):  # -1.0 parses as USub(Constant)
        yield from _literal_leaves(node.operand)
        return
    if isinstance(node, ast.Dict):
        # Only VALUES are pytree leaves; int/str keys are structure.
        for value in node.values:
            yield from _literal_leaves(value)
        return
    if isinstance(node, _CONTAINERS):
        for child in ast.iter_child_nodes(node):
            yield from _literal_leaves(child)


class ScanCarryWeakType(Rule):
    name = "scan-carry-weak-type"
    default_severity = "error"
    description = (
        "lax.scan carry initialized from a Python scalar literal — the "
        "weak-typed leaf promotes inside the body and forces a carry "
        "mismatch or a retrace per call; pin the dtype with jnp.asarray"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in _SCAN_NAMES:
                continue
            init = None
            if len(node.args) >= 2:
                init = node.args[1]
            else:
                for kw in node.keywords:
                    if kw.arg == "init":
                        init = kw.value
            if init is None:
                continue
            for leaf in _literal_leaves(init):
                yield (
                    leaf.lineno,
                    leaf.col_offset,
                    f"scan carry leaf `{ast.unparse(leaf)}` is a "
                    "weak-typed Python scalar — promotion inside the "
                    "body mismatches the carry (or silently retraces "
                    "per call); pin it with jnp.asarray(..., dtype) or "
                    "jnp.zeros_like",
                )
