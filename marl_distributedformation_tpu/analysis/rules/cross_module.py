"""cross-module-callback: host callbacks hidden behind imported helpers.

Rule 12 (``callback-in-hot-loop``) resolves one call hop INSIDE the
linted module: a ``lax.scan`` body calling a same-module helper that
performs ``io_callback``/``jax.debug.print`` is caught. The same hazard
wearing an import — ``from telemetry import emit`` (or ``import
telemetry; telemetry.emit(...)``) with the callback inside the imported
helper — was invisible to a strictly per-file pass. This rule closes
that hop: when a compiled loop body calls an imported name, the
imported module is located on disk (relative imports resolve against
the linted file; absolute imports search the file's ancestor
directories, which covers both sibling-module scripts and package
roots), parsed once (mtime-keyed cache), and the helper's own body is
scanned for direct callback calls. Still exactly one hop — a chain of
two imported helpers is out of scope for an AST pass and left to the
runtime transfer guard — and unresolvable modules (site-packages,
generated code) stay silent rather than guessing.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterator, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)
from marl_distributedformation_tpu.analysis.rules.callbacks import (
    _CALLBACK_CALLS,
    CallbackInHotLoop,
)

# How many ancestor directories of the linted file are searched as
# roots for absolute imports. Covers a package nested a few levels deep
# without walking to the filesystem root on every unresolvable import.
_MAX_ROOT_WALK = 6


class CrossModuleCallback(Rule):
    name = "cross-module-callback"
    default_severity = "error"
    description = (
        "a compiled loop body calls an imported helper whose body "
        "performs io_callback/pure_callback/jax.debug.print — a host "
        "round trip every scanned iteration, hidden one import away"
    )

    # Parsed-module cache shared across files and lint runs, keyed on
    # (path, mtime_ns) — rules are singletons (rules/__init__.py), so
    # a package-wide scan parses each imported module at most once.
    _tree_cache: Dict[Tuple[str, int], Optional[ast.Module]] = {}

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        from_imports, module_aliases = self._imports(ctx.tree)
        if not from_imports and not module_aliases:
            return
        reported: Set[Tuple[int, int]] = set()
        for body in CallbackInHotLoop._loop_bodies(ctx):
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._resolve_call(
                    ctx, node, from_imports, module_aliases
                )
                if hit and (node.lineno, node.col_offset) not in reported:
                    reported.add((node.lineno, node.col_offset))
                    called, module, callback = hit
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"{called}() is called from a compiled loop body "
                        f"and reaches {callback}(...) in imported module "
                        f"{module!r} — a host callback every scanned "
                        "iteration; hoist it out of the loop or stack "
                        "values into the scan output",
                    )

    # -- import surface ---------------------------------------------------

    @staticmethod
    def _imports(
        tree: ast.Module,
    ) -> Tuple[Dict[str, Tuple[str, str, int]], Dict[str, Tuple[str, int]]]:
        """``from_imports[local] = (module, attr, level)`` for
        ``from module import attr as local``;
        ``module_aliases[alias] = (module, 0)`` for
        ``import module [as alias]`` (a dotted ``import a.b`` binds the
        full dotted path — usage is ``a.b.f``)."""
        from_imports: Dict[str, Tuple[str, str, int]] = {}
        module_aliases: Dict[str, Tuple[str, int]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                module = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    from_imports[local] = (module, alias.name, node.level)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        module_aliases[alias.asname] = (alias.name, 0)
                    else:
                        module_aliases[alias.name] = (alias.name, 0)
        return from_imports, module_aliases

    # -- call resolution --------------------------------------------------

    def _resolve_call(
        self,
        ctx: ModuleContext,
        node: ast.Call,
        from_imports: Dict[str, Tuple[str, str, int]],
        module_aliases: Dict[str, Tuple[str, int]],
    ) -> Optional[Tuple[str, str, str]]:
        """``(called_name, module, callback)`` when this call reaches an
        imported helper that performs a host callback; else None."""
        if isinstance(node.func, ast.Name):
            name = node.func.id
            if name in ctx._defs_by_name:
                return None  # same-module def shadows: rule 12's domain
            imported = from_imports.get(name)
            if imported is None:
                return None
            module, attr, level = imported
            callback = self._callback_in_module_func(
                ctx.path, module, attr, level
            )
            if callback:
                return name, module or "." * level, callback
            return None
        fname = dotted_name(node.func)
        if not fname or "." not in fname:
            return None
        if fname in _CALLBACK_CALLS:
            return None  # direct callbacks are rule 12's finding
        prefix, _, attr = fname.rpartition(".")
        # `import pkg.mod` / `import pkg.mod as m` usage: m.f(...)
        aliased = module_aliases.get(prefix)
        if aliased is not None:
            module, level = aliased
            callback = self._callback_in_module_func(
                ctx.path, module, attr, level
            )
            if callback:
                return fname, module, callback
            return None
        # `from pkg import mod` usage: mod.f(...) — the imported name is
        # itself a module.
        head, _, rest = prefix.partition(".")
        imported = from_imports.get(head)
        if imported is not None and not rest:
            module, sub, level = imported
            full = f"{module}.{sub}" if module else sub
            callback = self._callback_in_module_func(
                ctx.path, full, attr, level
            )
            if callback:
                return fname, full, callback
        return None

    # -- module file resolution + scan ------------------------------------

    def _callback_in_module_func(
        self, path: str, module: str, func: str, level: int
    ) -> Optional[str]:
        """Does top-level function ``func`` of ``module`` (resolved
        relative to the linted file at ``path``) directly perform a host
        callback? One hop only; unresolvable modules answer no."""
        tree = self._module_tree(path, module, level)
        if tree is None:
            return None
        for node in tree.body:
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == func
            ):
                for inner in ast.walk(node):
                    if isinstance(inner, ast.Call):
                        fname = dotted_name(inner.func)
                        if fname in _CALLBACK_CALLS:
                            return fname
        return None

    def _module_tree(
        self, path: str, module: str, level: int
    ) -> Optional[ast.Module]:
        file = self._module_file(path, module, level)
        if file is None:
            return None
        try:
            key = (str(file), file.stat().st_mtime_ns)
        except OSError:
            return None
        if key not in self._tree_cache:
            try:
                tree: Optional[ast.Module] = ast.parse(
                    file.read_text(encoding="utf-8")
                )
            except (OSError, SyntaxError, UnicodeDecodeError):
                tree = None
            self._tree_cache[key] = tree
        return self._tree_cache[key]

    @staticmethod
    def _module_file(
        path: str, module: str, level: int
    ) -> Optional[Path]:
        base = Path(path).resolve().parent
        parts = module.split(".") if module else []
        if level > 0:
            # Relative import: `from .helpers import f` resolves against
            # the linted file's package, one parent per extra dot.
            root = base
            for _ in range(level - 1):
                root = root.parent
            roots = [root]
        else:
            roots = [base, *list(base.parents)[:_MAX_ROOT_WALK]]
        for root in roots:
            if parts:
                as_module = root.joinpath(*parts).with_suffix(".py")
                if as_module.is_file():
                    return as_module
                as_package = root.joinpath(*parts, "__init__.py")
                if as_package.is_file():
                    return as_package
        return None
