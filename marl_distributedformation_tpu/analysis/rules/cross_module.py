"""cross-module-callback: host callbacks hidden behind imported helpers.

Rule 12 (``callback-in-hot-loop``) owns chains that START inside the
linted module: a ``lax.scan`` body calling a same-module helper (or
method) that performs ``io_callback``/``jax.debug.print``. The same
hazard wearing an import — ``from telemetry import emit`` (or ``import
telemetry; telemetry.emit(...)``) with the callback inside the imported
helper — is this rule's report. Resolution and traversal run on the
shared call-graph engine (``analysis/callgraph.py``), which owns the
mtime-keyed cross-module parse cache this rule originally grew:
relative imports resolve against the linted file, absolute imports
search the file's ancestor directories, and the chain is followed
transitively to the engine's depth bound (an imported helper calling a
second helper — in its own module or back through another import — is
the same host round trip one more name away). Unresolvable modules
(site-packages, generated code) stay silent rather than guessing.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis import callgraph
from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)
from marl_distributedformation_tpu.analysis.rules.callbacks import (
    _CALLBACK_CALLS,
    CallbackInHotLoop,
)

_IMPORT_HOPS = frozenset({"import"})


def _callback_pred(node: ast.Call, fname) -> Optional[str]:
    return fname if fname in _CALLBACK_CALLS else None


class CrossModuleCallback(Rule):
    name = "cross-module-callback"
    default_severity = "error"
    description = (
        "a compiled loop body calls an imported helper whose body "
        "performs io_callback/pure_callback/jax.debug.print — a host "
        "round trip every scanned iteration, hidden one import away"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        reported: Set[Tuple[int, int]] = set()
        for body in CallbackInHotLoop._loop_bodies(ctx):
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                if dotted_name(node.func) in _CALLBACK_CALLS:
                    continue  # direct callbacks are rule 12's finding
                hit = callgraph.reachable_call(
                    ctx, node, _callback_pred, first_hops=_IMPORT_HOPS
                )
                if hit and (node.lineno, node.col_offset) not in reported:
                    reported.add((node.lineno, node.col_offset))
                    called = dotted_name(node.func) or "<callable>"
                    module = Path(hit.first_module).stem
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"{called}() is called from a compiled loop body "
                        f"and reaches {hit.matched}(...) in imported "
                        f"module {module!r} — a host callback every "
                        "scanned iteration; hoist it out of the loop or "
                        "stack values into the scan output",
                    )
