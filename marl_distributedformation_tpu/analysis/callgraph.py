"""Whole-repo call-graph + lock-context engine (the graftlock substrate).

graftlint's first 22 rules are per-module AST passes; five of them
(12/14/16/17/22) each grew a private "one call hop" walker because the
linter had no shared interprocedural view. This module is that view,
built once and cached:

1. **Call graph.** Every ``def`` / ``async def`` / ``lambda`` in a
   package becomes a :class:`FuncInfo`; call sites resolve through
   plain names, ``self.method``, attribute receivers typed via
   ``__init__`` annotations or direct construction, module aliases, and
   ``from m import f`` imports (the generalization of rule 14's private
   resolver). Traversals are depth-bounded (:data:`MAX_DEPTH`) —
   deep-enough chains belong to the runtime guards.

2. **Lock context.** ``with lock:`` blocks (including the
   ``getattr(obj, "batch_lock", None)`` + ``lock if lock is not None
   else nullcontext()`` gate idiom), explicit ``.acquire()`` calls
   (timed vs untimed), attribute writes, and callback registrations
   (``threading.Thread(target=...)``, ``Timer``, ``submit``,
   ``add_done_callback``, handler tables) are recorded per function
   with the with-stack held at each event, then propagated through
   resolved calls so "reachable while holding X" is a graph question.

3. **Annotations.** A small grammar declares intent the AST cannot:

   - ``# graftlock: guarded-by=<lock_attr>`` on an attribute
     assignment / dataclass field line declares the attr's guard;
   - ``# graftlock: holds=<lock_attr>`` on (or directly above) a
     ``def`` line asserts the caller-holds contract of a helper;
   - ``# graftlock: gate`` on a lock attr's declaration marks it a
     dispatch/batch gate (rule 25's subject; ``batch_lock`` is a gate
     by naming convention);
   - ``# graftlock: lock=<name>`` names a ``with``-item's lock when
     inference fails.

On top of these the engine computes the four graftlock analyses —
lock-ordering cycles over the may-acquire-while-holding graph,
unguarded writes to declared-guarded attributes from thread-reachable
code, blocking calls reachable under a dispatch gate, and callbacks
registered under a lock they re-acquire — once per package snapshot;
the rules in ``rules/graftlock.py`` just look their module's findings
up.

Caching: parses and per-module analyses are keyed on
``(path, mtime_ns, size)`` (rule 14's cache, generalized); the package
graph is keyed on the sorted snapshot of every member file, so editing
any module invalidates exactly one module analysis plus the package
pass. A lint of an in-memory module (path not on disk) analyzes that
module alone — fixture lints can never leak findings from the repo.

Lock identity: ``Class.attr`` when the owner class resolves, bare attr
name otherwise. Guard/held matching uses bare names (conservative
across instances); cycle edges connect qualified keys, skip same-name
pairs (N instances of one lock class, e.g. a coordinator sweeping every
replica's ``batch_lock``, are ordered by iteration, not nesting), and
only untimed acquisitions create edges — a timed acquire with an abort
path cannot deadlock.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import (
    Callable,
    Dict,
    FrozenSet,
    Iterator,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    dotted_name,
)

# Rule names the package pass computes findings for (defined here, not
# in rules/graftlock.py, so the engine never imports the rule layer).
LOCK_ORDERING_CYCLE = "lock-ordering-cycle"
UNGUARDED_SHARED_MUTATION = "unguarded-shared-mutation"
BLOCKING_UNDER_GATE = "blocking-call-under-dispatch-lock"
CALLBACK_LOCK_SEAM = "lock-released-across-await-seam"

# Transitive traversal bound: every analysis below follows resolved
# calls at most this many hops. Chains deeper than 8 frames are beyond
# what a static pass can report actionably; the runtime guards own them.
MAX_DEPTH = 8

# How many ancestor directories of a linted file are searched as roots
# for absolute imports (rule 14's constant, now engine-wide).
MAX_ROOT_WALK = 6

_ANNOT_RE = re.compile(r"#\s*graftlock:\s*([^#]+)")
_ANNOT_KEYS = frozenset({"guarded-by", "holds", "gate", "lock"})

# Attribute names that denote a lock-like synchronization object when no
# stronger signal (constructor, annotation) exists.
_LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|locks|barrier|mutex|cond|rlock)(?:$|_)")

_LOCK_CTORS = frozenset(
    {
        "threading.Lock", "threading.RLock", "threading.Condition",
        "threading.Semaphore", "threading.BoundedSemaphore",
        "Lock", "RLock", "Condition",
    }
)
_THREAD_CTORS = frozenset({"threading.Thread", "Thread"})
_TIMER_CTORS = frozenset({"threading.Timer", "Timer"})

# Container-mutation methods: calling one on a guarded attribute is a
# write to the shared structure.
_MUTATORS = frozenset(
    {
        "append", "appendleft", "extend", "add", "update", "pop", "popleft",
        "remove", "discard", "clear", "setdefault", "insert",
    }
)

# Gate-lock naming convention (rule 25): the fleet batch barrier.
_GATE_NAMES = frozenset({"batch_lock"})

# Blocking calls by dotted name (rule 25).
_BLOCKING_DOTTED = frozenset(
    {
        "jax.device_get", "device_get", "time.sleep",
        "urllib.request.urlopen", "requests.get", "requests.post",
        "socket.create_connection",
    }
)
_FILE_IO_ATTRS = frozenset(
    {"read_text", "write_text", "read_bytes", "write_bytes"}
)


def parse_annotations(line: str) -> Dict[str, List[str]]:
    """``# graftlock: key=value ...`` tokens on one source line. Parsing
    stops at the first token that is not a known key, so trailing prose
    ('gate — serving pause boundary') does not corrupt the payload."""
    m = _ANNOT_RE.search(line)
    out: Dict[str, List[str]] = {}
    if not m:
        return out
    for token in re.split(r"[\s,]+", m.group(1).strip()):
        if not token:
            continue
        key, eq, val = token.partition("=")
        if key not in _ANNOT_KEYS:
            break
        bucket = out.setdefault(key, [])
        if eq and val:
            bucket.append(val)
    return out


@dataclasses.dataclass(frozen=True)
class LockRef:
    """One lock object, as precisely as static analysis can name it."""

    name: str                     # attribute / variable name
    owner: Optional[str] = None   # owning class when resolvable

    @property
    def key(self) -> str:
        return f"{self.owner}.{self.name}" if self.owner else self.name


@dataclasses.dataclass(frozen=True)
class Acquire:
    lock: LockRef
    timed: bool
    line: int
    col: int
    via: str                      # "with" | "acquire"
    held: Tuple[LockRef, ...]     # with-stack at the acquisition point


@dataclasses.dataclass(frozen=True)
class AttrWrite:
    recv: str                     # "self", dotted receiver, or ""
    attr: str
    line: int
    col: int
    held: Tuple[LockRef, ...]
    in_init: bool


class CallSite:
    __slots__ = ("node", "line", "col", "held")

    def __init__(self, node: ast.Call, held: Tuple[LockRef, ...]) -> None:
        self.node = node
        self.line = node.lineno
        self.col = node.col_offset
        self.held = held


class Registration:
    """A callable handed to another execution context: thread target,
    timer, executor submit, done-callback, or a handler-table entry."""

    __slots__ = ("target", "kind", "line", "col", "held")

    def __init__(
        self, target: ast.AST, kind: str, line: int, col: int,
        held: Tuple[LockRef, ...],
    ) -> None:
        self.target = target
        self.kind = kind
        self.line = line
        self.col = col
        self.held = held


@dataclasses.dataclass
class FuncInfo:
    node: ast.AST
    name: str
    qualname: str
    class_name: Optional[str]
    module: "ModuleInfo"
    holds: Tuple[str, ...]                 # bare lock names asserted held
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    acquires: List[Acquire] = dataclasses.field(default_factory=list)
    writes: List[AttrWrite] = dataclasses.field(default_factory=list)
    registrations: List[Registration] = dataclasses.field(default_factory=list)

    def holds_refs(self) -> Tuple[LockRef, ...]:
        return tuple(LockRef(n, self.class_name) for n in self.holds)


@dataclasses.dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    methods: Dict[str, ast.AST]
    bases: List[str]
    attr_types: Dict[str, str]    # attr -> constructor/annotation dotted name
    guards: Dict[str, str]        # attr -> guard lock bare name
    gates: Set[str]               # lock attrs marked "# graftlock: gate"
    lock_attrs: Set[str]


def _is_lock_ctor(ctor: Optional[str]) -> bool:
    if not ctor:
        return False
    if ctor in _LOCK_CTORS:
        return True
    tail = ctor.rsplit(".", 1)[-1]
    return bool(re.search(r"(?:Lock|Barrier|Condition|Semaphore)$", tail))


def _timeout_bounded(node: ast.Call, *, first_arg_is_timeout: bool) -> bool:
    """Does this ``.acquire()`` / ``.wait()`` / ``.get()`` call carry a
    bound? Explicit ``timeout=None`` (and bare ``acquire(True)``) are
    unbounded; any other timeout expression counts as bounded."""
    for kw in node.keywords:
        if kw.arg == "timeout":
            return not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            )
    if node.args:
        first = node.args[0]
        if isinstance(first, ast.Constant):
            if first.value is False:
                return True   # non-blocking acquire: returns immediately
            if first.value in (True, None):
                return len(node.args) > 1
        return first_arg_is_timeout or len(node.args) > 1
    return False


def blocking_desc(node: ast.Call) -> Optional[str]:
    """Human-readable description when this call can block the calling
    thread indefinitely (or for a device round trip) — the shapes that
    wedge a fleet-wide serving pause when a dispatch gate is held."""
    fname = dotted_name(node.func)
    if fname in _BLOCKING_DOTTED:
        return f"{fname}(...)"
    if fname == "open":
        return "open(...) file IO"
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    recv = dotted_name(node.func.value) or ""
    if attr in _FILE_IO_ATTRS:
        return f"{recv or '<expr>'}.{attr}(...) file IO"
    if attr == "incident" and ("tracer" in recv or "flightrec" in recv):
        return f"{recv}.incident(...) flight-record file IO"
    if attr == "get" and "queue" in recv.rsplit(".", 1)[-1].lower():
        if not _timeout_bounded(node, first_arg_is_timeout=False):
            return f"{recv}.get() with no timeout"
    if attr == "acquire" and _LOCKISH_RE.search(recv.rsplit(".", 1)[-1]):
        if not _timeout_bounded(node, first_arg_is_timeout=False):
            return f"{recv}.acquire() with no timeout"
    if attr == "wait" and not _timeout_bounded(node, first_arg_is_timeout=True):
        if isinstance(node.func.value, (ast.Name, ast.Attribute)):
            return f"{recv}.wait() with no timeout"
    return None


# ----------------------------------------------------------------------
# Per-module analysis
# ----------------------------------------------------------------------


def _imports(
    tree: ast.Module,
) -> Tuple[Dict[str, Tuple[str, str, int]], Dict[str, Tuple[str, int]]]:
    """``from_imports[local] = (module, attr, level)`` and
    ``module_aliases[alias] = (module, 0)`` — rule 14's import surface,
    now shared by every interprocedural analysis."""
    from_imports: Dict[str, Tuple[str, str, int]] = {}
    module_aliases: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            module = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                from_imports[alias.asname or alias.name] = (
                    module, alias.name, node.level,
                )
        elif isinstance(node, ast.Import):
            for alias in node.names:
                module_aliases[alias.asname or alias.name] = (alias.name, 0)
    return from_imports, module_aliases


class ModuleInfo:
    """One module's call-graph facts: defs, classes, imports, and the
    per-function lock-context event streams."""

    def __init__(self, path: str, tree: ast.Module, source: str) -> None:
        self.path = path
        self.tree = tree
        self.lines = source.splitlines()
        self.from_imports, self.module_aliases = _imports(tree)
        self.classes: Dict[str, ClassInfo] = {}
        self.top_defs: Dict[str, ast.AST] = {}
        self.defs_by_name: Dict[str, List[ast.AST]] = {}
        self.functions: Dict[int, FuncInfo] = {}   # id(def node) -> info
        self.funcs: List[FuncInfo] = []

        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        self._parents = parents

        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.top_defs[node.name] = node
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.ClassDef):
                self._build_class(node)

        for node in ast.walk(tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                info = self._analyze_function(node)
                self.functions[id(node)] = info
                self.funcs.append(info)

    # -- structure -----------------------------------------------------

    def _enclosing_class(self, node: ast.AST) -> Optional[str]:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a def nested inside a method still belongs to the class
                cur = self._parents.get(cur)
                continue
            cur = self._parents.get(cur)
        return None

    def _build_class(self, node: ast.ClassDef) -> None:
        methods = {
            stmt.name: stmt
            for stmt in node.body
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        info = ClassInfo(
            name=node.name,
            node=node,
            methods=methods,
            bases=[dotted_name(b) or "" for b in node.bases],
            attr_types={},
            guards={},
            gates=set(),
            lock_attrs=set(),
        )
        # Class-body fields (dataclass style): `x: T = ...`.
        for stmt in node.body:
            target: Optional[str] = None
            ctor: Optional[str] = None
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                target = stmt.target.id
                ann = dotted_name(stmt.annotation)
                if ann:
                    info.attr_types[target] = ann
                if isinstance(stmt.value, ast.Call):
                    ctor = dotted_name(stmt.value.func)
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
                isinstance(stmt.targets[0], ast.Name)
            ):
                target = stmt.targets[0].id
                if isinstance(stmt.value, ast.Call):
                    ctor = dotted_name(stmt.value.func)
                    if ctor:
                        info.attr_types[target] = ctor
            if target is not None:
                self._note_attr(info, target, ctor, stmt.lineno)
        # `self.x = ...` anywhere in the class's methods.
        annotations = {}
        init = methods.get("__init__")
        if init is not None and not isinstance(init, ast.Lambda):
            annotations = {
                a.arg: dotted_name(a.annotation)
                for a in (*init.args.posonlyargs, *init.args.args,
                          *init.args.kwonlyargs)
                if a.annotation is not None
            }
        for method in methods.values():
            for stmt in ast.walk(method):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(stmt, ast.Assign):
                    targets, value = stmt.targets, stmt.value
                elif isinstance(stmt, ast.AnnAssign):
                    targets, value = [stmt.target], stmt.value
                for t in targets:
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    ctor = None
                    if isinstance(value, ast.Call):
                        ctor = dotted_name(value.func)
                        if ctor and t.attr not in info.attr_types:
                            info.attr_types[t.attr] = ctor
                    elif isinstance(value, ast.Name):
                        ann = annotations.get(value.id)
                        if ann and t.attr not in info.attr_types:
                            info.attr_types[t.attr] = ann
                    self._note_attr(info, t.attr, ctor, stmt.lineno)
        self.classes[node.name] = info

    def _note_attr(
        self, info: ClassInfo, attr: str, ctor: Optional[str], lineno: int
    ) -> None:
        """Record lock-ness and graftlock annotations for one attribute
        declaration line."""
        if _is_lock_ctor(ctor) or _LOCKISH_RE.search(attr):
            info.lock_attrs.add(attr)
        ann = self._line_annotations(lineno)
        for guard in ann.get("guarded-by", ()):
            info.guards[attr] = guard
        if "gate" in ann:
            info.gates.add(attr)
            info.lock_attrs.add(attr)

    def _line_annotations(self, lineno: int) -> Dict[str, List[str]]:
        if 1 <= lineno <= len(self.lines):
            return parse_annotations(self.lines[lineno - 1])
        return {}

    def _def_annotations(self, node: ast.AST) -> Dict[str, List[str]]:
        """Annotations on the def line or a comment-only line directly
        above it (mirroring suppression-comment placement)."""
        out = self._line_annotations(node.lineno)
        if not out and node.lineno >= 2:
            above = self.lines[node.lineno - 2]
            if above.lstrip().startswith("#"):
                out = parse_annotations(above)
        return out

    # -- per-function event streams ------------------------------------

    def _analyze_function(self, node: ast.AST) -> FuncInfo:
        class_name = self._enclosing_class(node)
        name = getattr(node, "name", "<lambda>")
        qualname = f"{class_name}.{name}" if class_name else name
        holds: Tuple[str, ...] = ()
        if not isinstance(node, ast.Lambda):
            holds = tuple(self._def_annotations(node).get("holds", ()))
        info = FuncInfo(
            node=node, name=name, qualname=qualname,
            class_name=class_name, module=self, holds=holds,
        )
        scanner = _FuncScanner(self, info)
        body = node.body if isinstance(node.body, list) else [node.body]
        scanner.scan_block(body)
        return info

    def class_of(self, name: Optional[str]) -> Optional[ClassInfo]:
        return self.classes.get(name) if name else None


class _FuncScanner:
    """Orders one function body, tracking the ``with``-stack of held
    locks and simple lock-valued locals, and emits the event streams.
    Nested defs/lambdas are skipped — a closure runs later, on some
    other stack, and inherits nothing."""

    def __init__(self, module: ModuleInfo, info: FuncInfo) -> None:
        self.module = module
        self.info = info
        self.held: List[LockRef] = []
        self.lock_locals: Dict[str, LockRef] = {}
        self.in_init = info.name in ("__init__", "__post_init__")

    def scan_block(self, stmts: Sequence[ast.AST]) -> None:
        for stmt in stmts:
            self._scan(stmt)

    def _scan(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._scan_with(node)
            return
        if isinstance(node, ast.Assign):
            self._scan_assign(node)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            self._record_writes([node.target])
        if isinstance(node, ast.Call):
            self._scan_call(node)
        for child in ast.iter_child_nodes(node):
            self._scan(child)

    # -- with blocks ---------------------------------------------------

    def _scan_with(self, node) -> None:
        pushed = 0
        forced = self.module._line_annotations(node.lineno).get("lock", [])
        for item in node.items:
            self._scan(item.context_expr)     # calls inside the item expr
            ref = self._lock_of(item.context_expr, require_lockish=True)
            if ref is None and forced:
                ref = LockRef(forced.pop(0))
            if ref is not None:
                self.info.acquires.append(
                    Acquire(
                        lock=ref, timed=False, line=node.lineno,
                        col=node.col_offset, via="with",
                        held=tuple(self.held),
                    )
                )
                self.held.append(ref)
                pushed += 1
        self.scan_block(node.body)
        for _ in range(pushed):
            self.held.pop()

    # -- assignments / writes ------------------------------------------

    def _scan_assign(self, node: ast.Assign) -> None:
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            ref = self._lock_of(node.value, require_lockish=False)
            if ref is not None:
                self.lock_locals[node.targets[0].id] = ref
        self._record_writes(node.targets)

    def _record_writes(self, targets: Sequence[ast.AST]) -> None:
        for t in targets:
            attr_node: Optional[ast.Attribute] = None
            if isinstance(t, ast.Attribute):
                attr_node = t
            elif isinstance(t, ast.Subscript) and isinstance(
                t.value, ast.Attribute
            ):
                attr_node = t.value
            elif isinstance(t, ast.Tuple):
                self._record_writes(t.elts)
                continue
            if attr_node is None:
                continue
            recv = dotted_name(attr_node.value) or ""
            self.info.writes.append(
                AttrWrite(
                    recv=recv, attr=attr_node.attr, line=t.lineno,
                    col=t.col_offset, held=tuple(self.held),
                    in_init=self.in_init,
                )
            )

    # -- calls ----------------------------------------------------------

    def _scan_call(self, node: ast.Call) -> None:
        held = tuple(self.held)
        self.info.calls.append(CallSite(node, held))
        fname = dotted_name(node.func)
        # explicit acquire: an acquisition event, not a held context
        if isinstance(node.func, ast.Attribute) and node.func.attr == "acquire":
            ref = self._lock_of(node.func.value, require_lockish=False)
            if ref is not None:
                self.info.acquires.append(
                    Acquire(
                        lock=ref,
                        timed=_timeout_bounded(
                            node, first_arg_is_timeout=False
                        ),
                        line=node.lineno, col=node.col_offset,
                        via="acquire", held=held,
                    )
                )
        # container mutation on an attribute = a write to it
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Attribute)
        ):
            recv_attr = node.func.value
            recv = dotted_name(recv_attr.value) or ""
            self.info.writes.append(
                AttrWrite(
                    recv=recv, attr=recv_attr.attr, line=node.lineno,
                    col=node.col_offset, held=held, in_init=self.in_init,
                )
            )
        # callback registrations
        self._scan_registrations(node, fname, held)

    def _scan_registrations(
        self, node: ast.Call, fname: Optional[str],
        held: Tuple[LockRef, ...],
    ) -> None:
        def reg(target: ast.AST, kind: str) -> None:
            self.info.registrations.append(
                Registration(target, kind, node.lineno, node.col_offset, held)
            )

        if fname in _THREAD_CTORS:
            for kw in node.keywords:
                if kw.arg == "target":
                    reg(kw.value, "thread")
        elif fname in _TIMER_CTORS:
            if len(node.args) >= 2:
                reg(node.args[1], "timer")
            for kw in node.keywords:
                if kw.arg == "function":
                    reg(kw.value, "timer")
        elif isinstance(node.func, ast.Attribute):
            if node.func.attr == "submit" and node.args:
                reg(node.args[0], "submit")
            elif node.func.attr == "add_done_callback" and node.args:
                reg(node.args[0], "done-callback")
        # handler tables: `Server({"register": self._rpc_register, ...})`
        for arg in (*node.args, *(kw.value for kw in node.keywords)):
            if isinstance(arg, ast.Dict):
                for value in arg.values:
                    if isinstance(value, (ast.Attribute, ast.Name)):
                        reg(value, "handler-table")

    # -- lock expression resolution -------------------------------------

    def _lock_of(
        self, expr: ast.AST, *, require_lockish: bool
    ) -> Optional[LockRef]:
        if isinstance(expr, ast.IfExp):
            return (
                self._lock_of(expr.body, require_lockish=require_lockish)
                or self._lock_of(expr.orelse, require_lockish=require_lockish)
            )
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                ref = self._lock_of(v, require_lockish=require_lockish)
                if ref is not None:
                    return ref
            return None
        if isinstance(expr, ast.Name):
            ref = self.lock_locals.get(expr.id)
            if ref is not None:
                return ref
            if _LOCKISH_RE.search(expr.id):
                return LockRef(expr.id)
            return None
        if isinstance(expr, ast.Attribute):
            owner = self._receiver_class(expr.value)
            cls = self.module.class_of(owner)
            is_lock = bool(
                (cls and expr.attr in cls.lock_attrs)
                or _LOCKISH_RE.search(expr.attr)
            )
            if is_lock or not require_lockish:
                return LockRef(expr.attr, owner) if is_lock or owner else (
                    LockRef(expr.attr)
                )
            return None
        if isinstance(expr, ast.Call):
            fname = dotted_name(expr.func)
            if fname and fname.rsplit(".", 1)[-1] == "getattr":
                if len(expr.args) >= 2 and isinstance(
                    expr.args[1], ast.Constant
                ) and isinstance(expr.args[1].value, str):
                    owner = self._receiver_class(expr.args[0])
                    return LockRef(expr.args[1].value, owner)
            if _is_lock_ctor(fname):
                return LockRef(fname.rsplit(".", 1)[-1].lower())
        return None

    def _receiver_class(self, expr: ast.AST) -> Optional[str]:
        """Class name owning the attributes of ``expr``: ``self`` is the
        enclosing class; ``self.x`` follows the inferred attr type."""
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return self.info.class_name
            return None
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ) and expr.value.id == "self":
            cls = self.module.class_of(self.info.class_name)
            if cls:
                t = cls.attr_types.get(expr.attr)
                if t:
                    return t.rsplit(".", 1)[-1]
        return None


# ----------------------------------------------------------------------
# Package graph + analyses
# ----------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EdgeSite:
    """Example site of a may-acquire-while-holding edge."""

    module_path: str
    qualname: str
    line: int
    col: int
    chain: Tuple[str, ...]        # held lock keys, in acquisition order
    acquired: str


class PackageGraph:
    """All modules of one package plus the graftlock analyses computed
    over them. ``findings[path][rule]`` holds ``(line, col, message)``
    triples the rules replay per linted module."""

    def __init__(
        self, modules: Dict[str, ModuleInfo], engine: "CallGraphEngine"
    ) -> None:
        self.modules = modules
        self._engine = engine
        self._member_paths = set(modules)
        self.lock_edges: Dict[Tuple[str, str], EdgeSite] = {}
        self.findings: Dict[str, Dict[str, List[Tuple[int, int, str]]]] = {}
        self.gate_names: Set[str] = set(_GATE_NAMES)
        self.guard_index: Dict[str, List[Tuple[str, str]]] = {}
        for mod in modules.values():
            for cls in mod.classes.values():
                self.gate_names |= cls.gates
                for attr, guard in cls.guards.items():
                    self.guard_index.setdefault(attr, []).append(
                        (cls.name, guard)
                    )
        self._analyze()

    # -- resolution ------------------------------------------------------

    def resolve_class(
        self, module: ModuleInfo, name: Optional[str]
    ) -> Optional[Tuple[ClassInfo, ModuleInfo]]:
        if not name:
            return None
        name = name.rsplit(".", 1)[-1]
        cls = module.classes.get(name)
        if cls is not None:
            return cls, module
        imported = module.from_imports.get(name)
        if imported is not None:
            target = self._engine.module_by_import(
                module.path, imported[0], imported[2]
            )
            if target is not None:
                cls = target.classes.get(imported[1])
                if cls is not None:
                    return cls, target
        return None

    def _method_of(
        self, module: ModuleInfo, class_name: Optional[str], method: str,
        _seen: Optional[Set[str]] = None,
    ) -> Optional[FuncInfo]:
        resolved = self.resolve_class(module, class_name)
        if resolved is None:
            return None
        cls, owner_mod = resolved
        node = cls.methods.get(method)
        if node is not None:
            return owner_mod.functions.get(id(node))
        seen = _seen or set()
        for base in cls.bases:
            if base and base not in seen:
                seen.add(base)
                hit = self._method_of(owner_mod, base, method, seen)
                if hit is not None:
                    return hit
        return None

    def resolve_call(
        self, module: ModuleInfo, node: ast.Call,
        class_name: Optional[str],
    ) -> List[Tuple[FuncInfo, str]]:
        """Possible callees of one call site as ``(func, kind)`` with
        kind in {"local", "import", "method"}."""
        out: List[Tuple[FuncInfo, str]] = []
        func = node.func
        if isinstance(func, ast.Name):
            name = func.id
            local = module.defs_by_name.get(name)
            if local:
                return [
                    (module.functions[id(d)], "local")
                    for d in local
                    if id(d) in module.functions
                ]
            if name in module.classes:
                init = self._method_of(module, name, "__init__")
                return [(init, "local")] if init else []
            imported = module.from_imports.get(name)
            if imported is not None:
                target = self._engine.module_by_import(
                    module.path, imported[0], imported[2]
                )
                if target is not None:
                    d = target.top_defs.get(imported[1])
                    if d is not None:
                        return [(target.functions[id(d)], "import")]
                    if imported[1] in target.classes:
                        init = self._method_of(
                            target, imported[1], "__init__"
                        )
                        return [(init, "import")] if init else []
            return out
        if not isinstance(func, ast.Attribute):
            return out
        attr = func.attr
        recv = func.value
        if isinstance(recv, ast.Name):
            if recv.id == "self":
                hit = self._method_of(module, class_name, attr)
                return [(hit, "method")] if hit else []
            # module alias (`import telemetry; telemetry.emit(...)`) or
            # from-imported module (`from pkg import mod; mod.f(...)`)
            modref = module.module_aliases.get(recv.id)
            level = 0
            if modref is None:
                imported = module.from_imports.get(recv.id)
                if imported is not None:
                    base, sub, level = imported
                    modref = (f"{base}.{sub}" if base else sub, level)
            if modref is not None:
                target = self._engine.module_by_import(
                    module.path, modref[0], level or modref[1]
                )
                if target is not None:
                    d = target.top_defs.get(attr)
                    if d is not None:
                        return [(target.functions[id(d)], "import")]
            return out
        if isinstance(recv, ast.Attribute) and isinstance(
            recv.value, ast.Name
        ) and recv.value.id == "self":
            # self.obj.m(): follow the inferred type of self.obj
            cls = module.class_of(class_name)
            if cls:
                t = cls.attr_types.get(recv.attr)
                if t:
                    hit = self._method_of(module, t, attr)
                    if hit:
                        return [(hit, "method")]
        return out

    def resolve_target(
        self, module: ModuleInfo, expr: ast.AST, class_name: Optional[str]
    ) -> List[FuncInfo]:
        """Resolve a callback-registration target expression."""
        if isinstance(expr, ast.Lambda):
            info = module.functions.get(id(expr))
            return [info] if info else []
        if isinstance(expr, ast.Name):
            defs = module.defs_by_name.get(expr.id, ())
            return [
                module.functions[id(d)]
                for d in defs
                if id(d) in module.functions
            ]
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ) and expr.value.id == "self":
            hit = self._method_of(module, class_name, expr.attr)
            return [hit] if hit else []
        return []

    # -- analyses --------------------------------------------------------

    def _add(
        self, path: str, rule: str, line: int, col: int, msg: str
    ) -> None:
        if path in self._member_paths:
            self.findings.setdefault(path, {}).setdefault(rule, []).append(
                (line, col, msg)
            )

    def _analyze(self) -> None:
        self._collect_contexts()
        self._detect_cycles()
        self._check_guarded_writes()

    # . lock edges + gate blocking + callback seams (one shared DFS) .....

    def _collect_contexts(self) -> None:
        seen_states: Set[Tuple[int, FrozenSet[str]]] = set()
        blocking_seen: Set[Tuple[str, int, int]] = set()
        seam_seen: Set[Tuple[str, int, int]] = set()

        def merge(
            base: Tuple[LockRef, ...], extra: Tuple[LockRef, ...]
        ) -> Tuple[LockRef, ...]:
            names = {r.name for r in base}
            return base + tuple(r for r in extra if r.name not in names)

        def visit(func: FuncInfo, held: Tuple[LockRef, ...], depth: int) -> None:
            entry = merge(held, func.holds_refs())
            state = (id(func), frozenset(r.name for r in entry))
            if state in seen_states:
                return
            seen_states.add(state)
            mod = func.module
            for acq in func.acquires:
                eff = merge(entry, acq.held)
                if not acq.timed:
                    for h in eff:
                        if h.name == acq.lock.name:
                            continue
                        edge = (h.key, acq.lock.key)
                        if edge not in self.lock_edges:
                            self.lock_edges[edge] = EdgeSite(
                                module_path=mod.path,
                                qualname=func.qualname,
                                line=acq.line, col=acq.col,
                                chain=tuple(r.key for r in eff),
                                acquired=acq.lock.key,
                            )
            for call in func.calls:
                eff = merge(entry, call.held)
                gates = [r for r in eff if r.name in self.gate_names]
                if gates:
                    desc = blocking_desc(call.node)
                    site = (mod.path, call.line, call.col)
                    if desc and site not in blocking_seen:
                        blocking_seen.add(site)
                        self._add(
                            mod.path, BLOCKING_UNDER_GATE, call.line,
                            call.col,
                            f"{desc} runs while dispatch gate "
                            f"{gates[0].key!r} is held (in "
                            f"{func.qualname}) — every replica's batch "
                            "barrier stays closed for the duration; move "
                            "it off the gated region or bound it with a "
                            "timeout",
                        )
                if depth > 0:
                    for callee, _ in self.resolve_call(
                        mod, call.node, func.class_name
                    ):
                        visit(callee, eff, depth - 1)
            for r in func.registrations:
                eff = merge(entry, r.held)
                if not eff:
                    continue
                for target in self.resolve_target(
                    mod, r.target, func.class_name
                ):
                    reacquired = self._reacquires(
                        target, {ref.name for ref in eff}
                    )
                    site = (mod.path, r.line, r.col)
                    if reacquired and site not in seam_seen:
                        seam_seen.add(site)
                        self._add(
                            mod.path, CALLBACK_LOCK_SEAM, r.line, r.col,
                            f"{r.kind} callback {target.qualname} is "
                            f"registered while {reacquired!r} is held and "
                            f"re-acquires {reacquired!r} when it runs — "
                            "if the registering thread waits on the "
                            "callback (or the callback can run "
                            "synchronously) this deadlocks; register "
                            "after releasing the lock",
                        )

        for mod in self.modules.values():
            for func in mod.funcs:
                visit(func, (), MAX_DEPTH)

    def _reacquires(
        self, func: FuncInfo, held_names: Set[str], depth: int = MAX_DEPTH,
        _seen: Optional[Set[int]] = None,
    ) -> Optional[str]:
        """Bare name of the first lock in ``held_names`` that ``func``
        transitively acquires, else None."""
        seen = _seen or set()
        if id(func) in seen:
            return None
        seen.add(id(func))
        for acq in func.acquires:
            if acq.lock.name in held_names:
                return acq.lock.name
        if depth > 0:
            for call in func.calls:
                for callee, _ in self.resolve_call(
                    func.module, call.node, func.class_name
                ):
                    hit = self._reacquires(
                        callee, held_names, depth - 1, seen
                    )
                    if hit:
                        return hit
        return None

    # . cycle detection ..................................................

    def _detect_cycles(self) -> None:
        graph: Dict[str, Set[str]] = {}
        for a, b in self.lock_edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        reported: Set[FrozenSet[str]] = set()
        for start in sorted(graph):
            cycle = self._find_cycle(graph, start)
            if cycle is None:
                continue
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            edges = [
                (cycle[i], cycle[(i + 1) % len(cycle)])
                for i in range(len(cycle))
            ]
            sites = [self.lock_edges[e] for e in edges]
            chain = "; ".join(
                f"holding {a!r} acquires {b!r} in {s.qualname} "
                f"({Path(s.module_path).name}:{s.line})"
                for (a, b), s in zip(edges, sites)
            )
            msg = (
                f"lock-ordering cycle "
                f"{' -> '.join([*cycle, cycle[0]])}: {chain} — two "
                "threads entering this cycle from different edges "
                "deadlock; acquire these locks in one global order (or "
                "make one acquisition timed with an abort path)"
            )
            for mod_path in {s.module_path for s in sites}:
                first = next(
                    s for s in sites if s.module_path == mod_path
                )
                self._add(
                    mod_path, LOCK_ORDERING_CYCLE, first.line, first.col,
                    msg,
                )

    @staticmethod
    def _find_cycle(
        graph: Dict[str, Set[str]], start: str
    ) -> Optional[List[str]]:
        """A simple cycle through ``start``, as a node list, else None."""
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        best: Optional[List[str]] = None
        seen_paths: Set[Tuple[str, ...]] = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start and len(path) > 1:
                    if best is None or len(path) < len(best):
                        best = list(path)
                    continue
                if nxt in path or len(path) >= 8:
                    continue
                key = tuple([*path, nxt])
                if key not in seen_paths:
                    seen_paths.add(key)
                    stack.append((nxt, [*path, nxt]))
        return best

    # . guarded writes ...................................................

    def _thread_entries(self) -> List[FuncInfo]:
        entries: List[FuncInfo] = []
        seen: Set[int] = set()
        for mod in self.modules.values():
            for func in mod.funcs:
                for r in func.registrations:
                    for target in self.resolve_target(
                        mod, r.target, func.class_name
                    ):
                        if id(target) not in seen:
                            seen.add(id(target))
                            entries.append(target)
        return entries

    def _guard_for(
        self, func: FuncInfo, write: AttrWrite
    ) -> Optional[Tuple[str, str]]:
        """``(guard, owner_class)`` when this write targets a declared-
        guarded attribute."""
        if write.recv == "self":
            resolved = self.resolve_class(func.module, func.class_name)
            seen: Set[str] = set()
            while resolved is not None:
                cls, owner_mod = resolved
                guard = cls.guards.get(write.attr)
                if guard is not None:
                    return guard, cls.name
                resolved = None
                for base in cls.bases:
                    if base and base not in seen:
                        seen.add(base)
                        resolved = self.resolve_class(owner_mod, base)
                        if resolved:
                            break
            return None
        declared = self.guard_index.get(write.attr, ())
        if len(declared) == 1:
            cls_name, guard = declared[0]
            return guard, cls_name
        return None

    def _check_guarded_writes(self) -> None:
        flagged: Set[Tuple[str, int, int]] = set()
        seen_states: Set[Tuple[int, FrozenSet[str]]] = set()

        def visit(func: FuncInfo, held: FrozenSet[str], depth: int) -> None:
            entry = held | set(func.holds)
            state = (id(func), frozenset(entry))
            if state in seen_states:
                return
            seen_states.add(state)
            mod = func.module
            for w in func.writes:
                if w.in_init and w.recv == "self":
                    continue   # pre-publication construction
                guarded = self._guard_for(func, w)
                if guarded is None:
                    continue
                guard, owner = guarded
                eff = entry | {r.name for r in w.held}
                site = (mod.path, w.line, w.col)
                if guard not in eff and site not in flagged:
                    flagged.add(site)
                    recv = w.recv or "<expr>"
                    self._add(
                        mod.path, UNGUARDED_SHARED_MUTATION, w.line, w.col,
                        f"{recv}.{w.attr} is declared guarded-by="
                        f"{guard!r} (on {owner}.{w.attr}) but is written "
                        f"from thread-reachable {func.qualname} without "
                        f"holding {guard!r} — wrap the write in `with "
                        f"...{guard}:` or move it onto the guarded path",
                    )
            if depth > 0:
                for call in func.calls:
                    eff = entry | {r.name for r in call.held}
                    for callee, _ in self.resolve_call(
                        mod, call.node, func.class_name
                    ):
                        visit(callee, frozenset(eff), depth - 1)

        for entry in self._thread_entries():
            visit(entry, frozenset(), MAX_DEPTH)

    # -- rule replay ------------------------------------------------------

    def findings_for(
        self, path: str, rule: str
    ) -> List[Tuple[int, int, str]]:
        return self.findings.get(path, {}).get(rule, [])


# ----------------------------------------------------------------------
# Engine: caches + package discovery
# ----------------------------------------------------------------------


def _file_key(path: Path) -> Optional[Tuple[str, int, int]]:
    try:
        st = path.stat()
    except OSError:
        return None
    return (str(path), st.st_mtime_ns, st.st_size)


class CallGraphEngine:
    """Process-global engine instance (:data:`ENGINE`). All caches are
    keyed on ``(path, mtime_ns, size)`` so an edited module re-resolves
    on the next lint without restarting the process."""

    def __init__(self) -> None:
        self._module_cache: Dict[Tuple[str, int, int], Optional[ModuleInfo]] = {}
        self._package_cache: Dict[str, Tuple[Tuple, PackageGraph]] = {}
        self._ctx_slot: Optional[Tuple[ModuleContext, PackageGraph]] = None
        self._ctx_cache: Dict[Tuple[str, int, int], ModuleContext] = {}

    # -- module loading ---------------------------------------------------

    def module(self, path: Path) -> Optional[ModuleInfo]:
        key = _file_key(path)
        if key is None:
            return None
        if key not in self._module_cache:
            try:
                source = path.read_text(encoding="utf-8")
                tree = ast.parse(source)
            except (OSError, SyntaxError, UnicodeDecodeError, ValueError):
                self._module_cache[key] = None
            else:
                self._module_cache[key] = ModuleInfo(str(path), tree, source)
        return self._module_cache[key]

    def context_for(self, module: ModuleInfo) -> ModuleContext:
        """A full ModuleContext (traced scopes, taint) for a module the
        engine loaded — rule 17's cross-module predicate needs both."""
        key = _file_key(Path(module.path)) or (module.path, 0, 0)
        ctx = self._ctx_cache.get(key)
        if ctx is None:
            ctx = ModuleContext(
                module.tree, "\n".join(module.lines), module.path
            )
            self._ctx_cache[key] = ctx
        return ctx

    # -- import resolution (rule 14's, generalized) -----------------------

    @staticmethod
    def module_file(
        path: str, module: str, level: int
    ) -> Optional[Path]:
        """Locate ``module`` (dotted) relative to the importing file at
        ``path``: relative imports resolve against the file's package;
        absolute imports search the file's ancestor directories."""
        base = Path(path).resolve().parent
        parts = module.split(".") if module else []
        if level > 0:
            root = base
            for _ in range(level - 1):
                root = root.parent
            roots = [root]
        else:
            roots = [base, *list(base.parents)[:MAX_ROOT_WALK]]
        for root in roots:
            if parts:
                as_module = root.joinpath(*parts).with_suffix(".py")
                if as_module.is_file():
                    return as_module
                as_package = root.joinpath(*parts, "__init__.py")
                if as_package.is_file():
                    return as_package
            elif level > 0:
                init = root / "__init__.py"
                if init.is_file():
                    return init
        return None

    def module_by_import(
        self, importer_path: str, module: str, level: int
    ) -> Optional[ModuleInfo]:
        file = self.module_file(importer_path, module, level)
        if file is None:
            return None
        return self.module(file)

    # -- package discovery -------------------------------------------------

    @staticmethod
    def package_files(path: Path) -> Tuple[Path, List[Path]]:
        """``(root, member_files)`` for the package containing ``path``:
        walk up while ``__init__.py`` exists (recursive scan of the
        package root); a bare directory (fixture tempdirs, scripts)
        scans non-recursively."""
        directory = path.parent
        root = directory
        while (root.parent / "__init__.py").is_file() and (
            root / "__init__.py"
        ).is_file():
            root = root.parent
        if (root / "__init__.py").is_file():
            files = sorted(root.rglob("*.py"))
        else:
            root = directory
            files = sorted(root.glob("*.py"))
        return root, files

    def package_for(self, ctx: ModuleContext) -> PackageGraph:
        """The PackageGraph covering ``ctx``'s module. In-memory modules
        (path not on disk) analyze alone; on-disk modules pull in their
        whole package, cached on the member-file snapshot."""
        # The slot holds a strong reference to the context it memoizes:
        # comparing a bare id() against a freed context's recycled
        # address would serve a stale graph for an edited file.
        slot = self._ctx_slot
        if slot is not None and slot[0] is ctx:
            return slot[1]
        path = Path(ctx.path)
        if not path.exists():
            source = "\n".join(ctx.lines)
            mod = ModuleInfo(ctx.path, ctx.tree, source)
            pg = PackageGraph({ctx.path: mod}, self)
        else:
            root, files = self.package_files(path.resolve())
            snapshot = tuple(
                k for k in (_file_key(f) for f in files) if k is not None
            )
            cached = self._package_cache.get(str(root))
            if cached is not None and cached[0] == snapshot:
                pg = cached[1]
            else:
                modules: Dict[str, ModuleInfo] = {}
                for f in files:
                    mod = self.module(f)
                    if mod is not None:
                        modules[str(f)] = mod
                pg = PackageGraph(modules, self)
                self._package_cache[str(root)] = (snapshot, pg)
        self._ctx_slot = (ctx, pg)
        return pg

    def module_key_for(self, ctx: ModuleContext) -> str:
        path = Path(ctx.path)
        return str(path.resolve()) if path.exists() else ctx.path


ENGINE = CallGraphEngine()


# ----------------------------------------------------------------------
# Reachability helpers for the migrated per-module rules (12/14/16/17/22)
# ----------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReachHit:
    """A transitive hit: what matched, where the chain entered."""

    matched: str                  # description from the predicate
    first_qualname: str
    first_kind: str               # "local" | "import" | "method"
    first_module: str             # path of the first callee's module
    hops: int


def _ctx_module(ctx: ModuleContext, pg: PackageGraph) -> Optional[ModuleInfo]:
    return pg.modules.get(ENGINE.module_key_for(ctx))


def _enclosing_class_name(ctx: ModuleContext, node: ast.AST) -> Optional[str]:
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = ctx.parents.get(cur)
    return None


def traced_in_own_module(func: FuncInfo, home_ctx: ModuleContext) -> bool:
    """Is ``func`` a traced scope of its own module? (Prune predicate:
    a traced callee compiles with the loop — its probes/branches are
    in-program, not host-side.)"""
    if func.module.path == home_ctx.path:
        owner = home_ctx
    else:
        owner = ENGINE.context_for(func.module)
    return func.node in owner.traced_scopes


def reachable_call(
    ctx: ModuleContext,
    call: ast.Call,
    pred: Callable[[ast.Call, Optional[str]], Optional[str]],
    *,
    first_hops: FrozenSet[str] = frozenset({"local", "method", "import"}),
    depth: int = MAX_DEPTH,
    prune: Optional[Callable[[FuncInfo], bool]] = None,
) -> Optional[ReachHit]:
    """Does ``call``'s callee transitively reach a call satisfying
    ``pred(call_node, dotted_name)``? The first hop's kind must be in
    ``first_hops`` (rules 12 and 14 split local-vs-imported chains so
    their reports stay disjoint); deeper hops follow every resolvable
    edge. A callee for which ``prune`` answers True is neither scanned
    nor descended into. The direct call itself is NOT tested — direct
    hits stay the per-module rules' own business."""
    pg = ENGINE.package_for(ctx)
    module = _ctx_module(ctx, pg)
    if module is None:
        return None
    class_name = _enclosing_class_name(ctx, call)
    callees = pg.resolve_call(module, call, class_name)
    for first, kind in callees:
        if kind not in first_hops:
            continue
        if prune is not None and prune(first):
            continue
        hit = _search_calls(pg, first, pred, depth, {id(first)}, 1, prune)
        if hit is not None:
            matched, hops = hit
            return ReachHit(
                matched=matched,
                first_qualname=first.qualname,
                first_kind=kind,
                first_module=first.module.path,
                hops=hops,
            )
    return None


def _search_calls(
    pg: PackageGraph,
    func: FuncInfo,
    pred: Callable[[ast.Call, Optional[str]], Optional[str]],
    depth: int,
    seen: Set[int],
    hops: int,
    prune: Optional[Callable[[FuncInfo], bool]] = None,
) -> Optional[Tuple[str, int]]:
    for site in func.calls:
        matched = pred(site.node, dotted_name(site.node.func))
        if matched is not None:
            return matched, hops
    if depth <= 1:
        return None
    for site in func.calls:
        for callee, _ in pg.resolve_call(
            func.module, site.node, func.class_name
        ):
            if id(callee) in seen:
                continue
            seen.add(id(callee))
            if prune is not None and prune(callee):
                continue
            hit = _search_calls(
                pg, callee, pred, depth - 1, seen, hops + 1, prune
            )
            if hit is not None:
                return hit
    return None


def reachable_function(
    ctx: ModuleContext,
    call: ast.Call,
    func_pred: Callable[[FuncInfo, ModuleContext], Optional[str]],
    *,
    depth: int = MAX_DEPTH,
) -> Optional[ReachHit]:
    """Like :func:`reachable_call`, but the predicate inspects each
    reachable FUNCTION (with its own module's ModuleContext) instead of
    each call site — rule 17's shape."""
    pg = ENGINE.package_for(ctx)
    module = _ctx_module(ctx, pg)
    if module is None:
        return None
    class_name = _enclosing_class_name(ctx, call)

    def visit(
        func: FuncInfo, kind: str, first: FuncInfo, d: int,
        seen: Set[int], hops: int,
    ) -> Optional[ReachHit]:
        owner_ctx = (
            ctx if func.module is module
            else ENGINE.context_for(func.module)
        )
        matched = func_pred(func, owner_ctx)
        if matched is not None:
            return ReachHit(
                matched=matched,
                first_qualname=first.qualname,
                first_kind=kind,
                first_module=first.module.path,
                hops=hops,
            )
        if d <= 1:
            return None
        for site in func.calls:
            for callee, _ in pg.resolve_call(
                func.module, site.node, func.class_name
            ):
                if id(callee) in seen:
                    continue
                seen.add(id(callee))
                hit = visit(callee, kind, first, d - 1, seen, hops + 1)
                if hit is not None:
                    return hit
        return None

    for first, kind in pg.resolve_call(module, call, class_name):
        hit = visit(first, kind, first, depth, {id(first)}, 1)
        if hit is not None:
            return hit
    return None
