"""Runtime tracing guards: the dynamic half of graftlint.

The AST linter (linter.py) sees one file at a time; these guards watch
the properties that only exist at run time:

- :class:`RetraceGuard` — counts how many times a jit target is actually
  traced and (optionally) fails the process past a budget. Accidental
  retracing is the #1 silent throughput killer in JAX: a weak-typed
  scalar or a drifting static arg recompiles a multi-second XLA program
  every iteration and nothing crashes.
- :func:`no_host_transfers` — a ``jax.transfer_guard_device_to_host``
  context for the trainer hot loop: any ``.item()`` / ``float()`` /
  implicit ``__array__`` sync inside the guarded region raises instead
  of silently serializing the dispatch pipeline (on a tunneled TPU each
  sync pays a full RTT).
- :func:`nan_guard` — scoped ``jax_debug_nans`` toggle: XLA re-runs any
  op that produced a NaN in op-by-op mode and raises at the source op.
- :func:`ledgered_jit` / :class:`LedgerDispatch` — the RetraceGuard seam
  extended into the ProgramLedger (``obs/ledger.py``): swap
  ``jax.jit(guard.wrap(f), **kw)`` for ``ledgered_jit(f, guard, **kw)``
  and every compilation of the target registers its executable's cost/
  memory facts and build timings automatically, plus a per-dispatch
  latency sample at the same host seam. This file owns ALL the
  jax-touching extraction (executable claiming, lowered cost analysis,
  ``jax.monitoring`` compile-event attribution); the ledger itself
  stays jax-free.

All are re-exported through ``utils.profiling`` and opt-in from
``train.trainer.TrainConfig`` (``guard_retraces`` / ``guard_transfers``
/ ``guard_nans``).
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax

from marl_distributedformation_tpu.obs.ledger import get_ledger, sanitize_key


class RetraceError(RuntimeError):
    """A guarded jit target compiled more often than its budget allows."""


class RetraceGuard:
    """Count (and optionally bound) the traces of a jit target.

    Wrap the Python callable BEFORE handing it to ``jax.jit``: the
    wrapper body runs exactly once per trace (jit executes the Python
    function only on cache miss), so ``count`` equals the number of
    compilations this process triggered for it.

    >>> guard = RetraceGuard("train_iteration", max_traces=2)
    >>> step = jax.jit(guard.wrap(step_fn), donate_argnums=(0,))

    ``max_traces=None`` only counts. With a budget, the trace that
    exceeds it raises :class:`RetraceError` naming the argument
    signature that caused it — at the retrace, where the stack still
    shows which caller changed shapes/dtypes.
    """

    def __init__(
        self, name: str = "jit-target", max_traces: Optional[int] = None
    ) -> None:
        self.name = name
        self.max_traces = max_traces
        self._lock = threading.Lock()
        self.count = 0

    def reset(self) -> None:
        with self._lock:
            self.count = 0

    def _describe(self, args: Any, kwargs: Any) -> str:
        def leaf(x: Any) -> str:
            shape = getattr(x, "shape", None)
            dtype = getattr(x, "dtype", None)
            if shape is None or dtype is None:
                return f"{type(x).__name__}:{x!r}"[:40]
            return f"{dtype}{list(shape)}"

        leaves = jax.tree_util.tree_leaves((args, kwargs))
        head = ", ".join(leaf(x) for x in leaves[:8])
        extra = len(leaves) - 8
        return head + (f", … +{extra} leaves" if extra > 0 else "")

    def wrap(self, fn: Callable[..., Any]) -> Callable[..., Any]:
        @functools.wraps(fn)
        def traced(*args: Any, **kwargs: Any) -> Any:
            if getattr(_INTROSPECT, "active", False):
                # A ledger-initiated re-lowering (cache-hit in the
                # common case; see _register_program) must never
                # consume trace budget — observability cannot become a
                # RetraceError.
                return fn(*args, **kwargs)
            with self._lock:
                self.count += 1
                count = self.count
            if self.max_traces is not None and count > self.max_traces:
                raise RetraceError(
                    f"{self.name!r} traced {count} times "
                    f"(budget {self.max_traces}) — a shape, dtype, "
                    "weak-type, or static-arg drift is forcing "
                    "recompilation every call; offending signature: "
                    f"[{self._describe(args, kwargs)}]"
                )
            try:
                return fn(*args, **kwargs)
            except Exception:
                # A trace that raises produced no compiled program (and
                # no jit cache entry), so it must not consume budget —
                # otherwise one malformed call poisons the target for
                # every valid caller after it (the serving engine leans
                # on this: budget-1 per bucket must mean one SUCCESSFUL
                # compile, not one attempt).
                with self._lock:
                    self.count -= 1
                raise

        return traced


@contextlib.contextmanager
def no_host_transfers(level: str = "disallow") -> Iterator[None]:
    """Forbid device->host transfers in the wrapped region.

    Device-to-host only: host-to-device constant uploads during
    compilation are part of tracing and stay allowed — the hot-loop
    poison is the reverse direction (``.item()``, ``float()``, implicit
    ``np.asarray``), which serializes the dispatch pipeline behind a
    sync. ``level`` follows ``jax.transfer_guard``: ``"disallow"``
    raises, ``"log"`` prints and continues (triage mode).

    Backend caveat: the XLA CPU backend aliases device and host memory,
    so readbacks there are zero-copy and the guard never fires — it is a
    no-op on CPU and enforceable on TPU/GPU. The static complement
    (graftlint's host-sync-in-jit rule) catches spelled-out syncs on
    every backend; this guard catches the implicit ones on hardware,
    which is where they cost real RTTs.
    """
    with jax.transfer_guard_device_to_host(level):
        yield


# ----------------------------------------------------------------------
# ProgramLedger glue: the RetraceGuard seam extended below the dispatch
# boundary (obs/ledger.py holds the jax-free record side).
# ----------------------------------------------------------------------

# Thread-local flag marking ledger-initiated introspection (a `.lower()`
# against the already-traced signature): RetraceGuard.wrap skips budget
# accounting under it, so analysis can never trip a budget-1 receipt.
_INTROSPECT = threading.local()

# Thread-local stack of per-dispatch timing sinks for jax.monitoring
# compile-event attribution: trace, MLIR lowering, and backend compile
# all happen on the dispatching thread between our call entry and exit,
# so the innermost active dispatch owns any event that fires.
_MONITOR = threading.local()
_MONITOR_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration": "trace_seconds",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower_seconds",
    "/jax/core/compile/backend_compile_duration": "compile_seconds",
}
_monitor_installed = False


def _on_compile_event(event: str, duration: float, **_: Any) -> None:
    stack = getattr(_MONITOR, "stack", None)
    if not stack:
        return
    field = _MONITOR_EVENTS.get(event)
    if field is not None:
        sink = stack[-1]
        sink[field] = sink.get(field, 0.0) + float(duration)


def _install_monitor() -> None:
    global _monitor_installed
    if _monitor_installed:
        return
    _monitor_installed = True  # one attempt only, even on failure
    try:
        jax.monitoring.register_event_duration_secs_listener(
            _on_compile_event
        )
    except Exception:  # noqa: BLE001 — attribution is best-effort
        pass


@contextlib.contextmanager
def _ledger_introspection() -> Iterator[None]:
    prev = getattr(_INTROSPECT, "active", False)
    _INTROSPECT.active = True
    try:
        yield
    finally:
        _INTROSPECT.active = prev


def _abstract_signature(args: Any, kwargs: Any) -> Tuple[str, int]:
    """``(fingerprint, argument_bytes)`` of a call's abstract signature.
    Shape/dtype metadata only — safe on donated (deleted) arrays, whose
    avals outlive their buffers."""
    parts = []
    nbytes = 0
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            parts.append(f"py_{type(leaf).__name__}")
            continue
        parts.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
        size = getattr(leaf, "nbytes", None)
        if size is not None:
            nbytes += int(size)
    head = ", ".join(parts[:24])
    if len(parts) > 24:
        head += f", … +{len(parts) - 24} leaves"
    return f"{len(parts)} leaves: {head}", nbytes


# Claimed backend executables (by wrapper identity — live_executables()
# returns stable Python objects) and their cached HLO module names, so
# N registrations never re-deserialize the same modules. The nanobind
# LoadedExecutable rejects weakrefs, so lifetime management is explicit:
# every claim scan prunes ids no longer among the live executables —
# which both bounds the dicts (by LIVE executables, not executables
# ever seen) and retires a dead executable's claim/name before CPython
# can hand its address to a new one (id-reuse misattribution).
_claim_lock = threading.Lock()
_claimed_executables: set = set()
_executable_names: Dict[int, str] = {}


def _claim_executable(module_name: str, expected_arg_bytes: int) -> Any:
    """The backend's newest unclaimed live executable whose HLO module
    name matches (preferring an exact argument-size match when several
    same-named programs exist). None when the backend exposes no
    executable handles — callers fall back to lowered-cost analysis."""
    try:
        exes = jax.devices()[0].client.live_executables()
    except Exception:  # noqa: BLE001 — backend without the handle API
        return None
    with _claim_lock:
        current = {id(exe) for exe in exes}
        for stale in [
            i for i in _executable_names if i not in current
        ]:
            _executable_names.pop(stale, None)
        _claimed_executables.intersection_update(current)
        matches = []
        for exe in reversed(exes):  # newest last in creation order
            ident = id(exe)
            if ident in _claimed_executables:
                continue
            name = _executable_names.get(ident)
            if name is None:
                try:
                    name = exe.hlo_modules()[0].name
                except Exception:  # noqa: BLE001
                    name = "?"
                _executable_names[ident] = name
            if name == module_name:
                matches.append(exe)
        if not matches:
            return None
        chosen = None
        if expected_arg_bytes:
            for exe in matches:
                try:
                    stats = exe.get_compiled_memory_stats()
                    if stats.argument_size_in_bytes == expected_arg_bytes:
                        chosen = exe
                        break
                except Exception:  # noqa: BLE001
                    break
        chosen = chosen if chosen is not None else matches[0]
        _claimed_executables.add(id(chosen))
        return chosen


def _executable_facts(exe: Any) -> Dict[str, float]:
    """Cost + memory facts off a backend LoadedExecutable (or a
    jax.stages.Compiled — same method surface for cost analysis)."""
    facts: Dict[str, float] = {}
    try:
        cost = exe.cost_analysis()
        first = (
            cost[0] if isinstance(cost, (list, tuple)) and cost else cost
        )
        if isinstance(first, dict):
            if first.get("flops") is not None:
                facts["flops"] = float(first["flops"])
            if first.get("bytes accessed") is not None:
                facts["bytes_accessed"] = float(first["bytes accessed"])
    except Exception:  # noqa: BLE001 — partial facts beat no facts
        pass
    stats = None
    for getter in ("get_compiled_memory_stats", "memory_analysis"):
        fn = getattr(exe, getter, None)
        if fn is None:
            continue
        try:
            stats = fn()
            break
        except Exception:  # noqa: BLE001
            continue
    if stats is not None:
        for field, attr in (
            ("argument_bytes", "argument_size_in_bytes"),
            ("output_bytes", "output_size_in_bytes"),
            ("temp_bytes", "temp_size_in_bytes"),
            ("alias_bytes", "alias_size_in_bytes"),
            ("generated_code_bytes", "generated_code_size_in_bytes"),
        ):
            v = getattr(stats, attr, None)
            if v is not None:
                facts[field] = float(v)
    if not facts.get("generated_code_bytes"):
        try:
            v = getattr(exe, "size_of_generated_code_in_bytes", None)
            if callable(v):  # a method on backend LoadedExecutables
                v = v()
            if v:
                facts["generated_code_bytes"] = float(v)
        except Exception:  # noqa: BLE001
            pass
    return facts


class LedgerDispatch:
    """Callable wrapper around a guarded jitted program: the compile
    seam that feeds the ProgramLedger.

    Every call dispatches straight through; when the call compiled a
    new program (detected via the jit cache size, so a guard shared
    across several programs — the hetero sweep's per-chunk-length cache
    — attributes correctly), the new executable is registered with its
    cost/memory facts, abstract-signature fingerprint, donation map,
    and monitoring-attributed build timings. Each call also records one
    dispatch-latency sample under the wrapper's stable dispatch key
    (replicas sharing a program shape pool into one histogram).

    Disabled ledger: one attribute read, then the bare jitted call —
    and registration never raises into the dispatch path.
    """

    def __init__(
        self,
        jitted: Any,
        guard: RetraceGuard,
        *,
        subsystem: str,
        name: str,
        module_name: str,
        donate_argnums: Tuple[int, ...] = (),
    ) -> None:
        self._jitted = jitted
        self.guard = guard
        self.subsystem = subsystem
        self.name = name
        self.module_name = module_name
        self.donate_argnums = tuple(donate_argnums)
        self.dispatch_key = sanitize_key(f"{subsystem}_{name}")
        self._registered = 0
        self._traces = 0
        self._register_lock = threading.Lock()
        _install_monitor()

    # jit surface passthrough (.lower(), ._cache_size(), ...): callers
    # that treated the wrapped object as a jitted function keep working.
    def __getattr__(self, attr: str) -> Any:
        return getattr(self._jitted, attr)

    def _note_trace(self) -> None:
        """Called from inside the traced wrapper on each SUCCESSFUL
        trace of this program (never under ledger introspection) — the
        per-wrapper compile count. The guard's own count is not usable
        here: several programs can share one guard (the hetero sweep's
        per-chunk-length cache), and the C++ jit-cache size overcounts
        (donated outputs fed back as inputs mint new fastpath entries
        without any retrace)."""
        with self._register_lock:
            self._traces += 1

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        ledger = get_ledger()
        if not ledger.enabled:
            return self._jitted(*args, **kwargs)
        timings: Dict[str, float] = {}
        if self._registered == 0:
            # Compile-event attribution costs two thread-local touches
            # per call — paid only until the first registration. A
            # later re-compile (count-only guards) still registers,
            # with the first-dispatch wall as its build timing.
            stack = getattr(_MONITOR, "stack", None)
            if stack is None:
                stack = _MONITOR.stack = []
            stack.append(timings)
            t0 = time.perf_counter()
            try:
                out = self._jitted(*args, **kwargs)
            finally:
                stack.pop()
        else:
            t0 = time.perf_counter()
            out = self._jitted(*args, **kwargs)
        wall = time.perf_counter() - t0
        compiled = self._traces
        if compiled > self._registered:
            with self._register_lock:
                if compiled > self._registered:
                    self._registered = compiled
                    try:
                        self._register(ledger, args, kwargs, wall, timings)
                    except Exception:  # noqa: BLE001 — observability
                        pass  # must never fail the dispatch it observes
        else:
            # Steady-state dispatches only: the compiling call's wall
            # is a BUILD event (recorded as first_dispatch_seconds),
            # and folding it into the latency histogram would hand a
            # low-traffic program a compile-sized p95.
            ledger.dispatch(self.dispatch_key, wall)
        return out

    def _register(
        self,
        ledger: Any,
        args: Any,
        kwargs: Any,
        wall: float,
        timings: Dict[str, float],
    ) -> None:
        fingerprint, arg_bytes = _abstract_signature(args, kwargs)
        facts: Dict[str, float] = {}
        source = "unavailable"
        error: Optional[str] = None
        exe = _claim_executable(self.module_name, arg_bytes)
        if exe is not None:
            try:
                facts = _executable_facts(exe)
            except Exception as e:  # noqa: BLE001 — degrade to lowered
                facts, error = {}, repr(e)[:200]
            if facts:
                source = "executable"
        if source == "unavailable":
            # Pre-compile HLO estimates off the cached lowering: the
            # jaxpr cache holds this call's trace, so no re-trace in
            # the common case — and the introspection flag keeps a
            # cache miss out of the guard budget regardless.
            try:
                with _ledger_introspection():
                    lowered = self._jitted.lower(*args, **kwargs)
                facts = _executable_facts(lowered)
                if facts:
                    source = "lowered"
            except Exception as e:  # noqa: BLE001
                error = repr(e)[:200]
        all_timings = dict(timings)
        all_timings["first_dispatch_seconds"] = wall
        ledger.register(
            name=self.name,
            subsystem=self.subsystem,
            fingerprint=fingerprint,
            donate_argnums=self.donate_argnums,
            backend=jax.default_backend(),
            timings=all_timings,
            facts=facts,
            analysis_source=source,
            analysis_error=error,
            dispatch_key=self.dispatch_key,
        )


def ledgered_jit(
    fn: Callable[..., Any],
    guard: RetraceGuard,
    *,
    subsystem: str,
    program: Optional[str] = None,
    **jit_kwargs: Any,
) -> LedgerDispatch:
    """``jax.jit(guard.wrap(fn), **jit_kwargs)`` with automatic
    ProgramLedger registration — the one-line seam every budget-1
    compile site adopts.

    ``program`` names the ledger entry (default: the function's own
    name) and is stamped onto the traced function so the compiled HLO
    module carries it too — which is both nicer profiles and what lets
    the ledger claim the executable back from the backend by name.
    """
    name = program or getattr(fn, "__name__", None) or "program"
    stamped = sanitize_key(name)
    if getattr(fn, "__name__", None) != stamped:
        try:
            fn.__name__ = stamped
        except (AttributeError, TypeError):
            # functools.partial / vmap wrappers reject attribute writes:
            # interpose a named def so the module name still matches.
            inner = fn

            def _named(*args: Any, **kwargs: Any) -> Any:
                return inner(*args, **kwargs)

            _named.__name__ = stamped
            fn = _named
    # The trace-counting layer sits between the guard wrapper and jit:
    # it runs exactly once per successful trace of THIS program (the
    # guard has already enforced its budget underneath), feeding the
    # wrapper-local compile count registration keys off.
    guarded = guard.wrap(fn)
    holder: list = []

    @functools.wraps(guarded)
    def counted(*args: Any, **kwargs: Any) -> Any:
        out = guarded(*args, **kwargs)
        if holder and not getattr(_INTROSPECT, "active", False):
            holder[0]._note_trace()
        return out

    jitted = jax.jit(counted, **jit_kwargs)
    donate = jit_kwargs.get("donate_argnums") or ()
    if isinstance(donate, int):
        donate = (donate,)
    dispatch = LedgerDispatch(
        jitted,
        guard,
        subsystem=subsystem,
        name=name,
        module_name=f"jit_{stamped}",
        donate_argnums=tuple(donate),
    )
    holder.append(dispatch)
    return dispatch


def register_aot_program(
    *,
    name: str,
    subsystem: str,
    compiled: Any,
    fingerprint: str = "",
    donate_argnums: Tuple[int, ...] = (),
    timings: Optional[Dict[str, float]] = None,
    dispatch_key: Optional[str] = None,
) -> Optional[str]:
    """Register an explicitly lowered+compiled executable (the sharded
    serving AOT path): the caller already holds the ``jax.stages
    .Compiled``, so the facts come straight off it and the measured
    lower/compile walls ride as the timings. Returns the ledger key
    (None when the ledger is disabled)."""
    ledger = get_ledger()
    if not ledger.enabled:
        return None
    try:
        facts = _executable_facts(compiled)
    except Exception:  # noqa: BLE001
        facts = {}
    return ledger.register(
        name=name,
        subsystem=subsystem,
        fingerprint=fingerprint,
        donate_argnums=donate_argnums,
        backend=jax.default_backend(),
        timings=timings,
        facts=facts,
        analysis_source="aot" if facts else "unavailable",
        dispatch_key=dispatch_key,
    )


def device_memory_bytes() -> Optional[float]:
    """Device memory in use across local devices: the PJRT
    ``memory_stats`` gauge where the backend keeps one (TPU/GPU), the
    summed live-buffer footprint otherwise (CPU — exact, since device
    and host memory alias there). None when neither is answerable."""
    try:
        devices = jax.local_devices()
        total = 0.0
        counted = False
        for dev in devices:
            stats = getattr(dev, "memory_stats", lambda: None)()
            if stats and stats.get("bytes_in_use") is not None:
                total += float(stats["bytes_in_use"])
                counted = True
        if counted:
            return total
        client = devices[0].client
        return float(
            sum(
                int(getattr(buf, "nbytes", 0) or 0)
                for buf in client.live_buffers()
            )
        )
    except Exception:  # noqa: BLE001 — a gauge, not a contract
        return None


_watermark_lock = threading.Lock()
_watermark_last = 0.0


def sample_device_watermark(
    min_interval_s: float = 5.0, force: bool = False
) -> Optional[float]:
    """Record the current device-memory footprint into the ledger's
    watermark gauge (called at drain/swap boundaries — host seams
    where a sync already happened). One attribute read when the ledger
    is disabled.

    Rate-limited: the CPU fallback walks every live buffer (~35 ms at
    5k arrays), which a per-chunk drain seam must not pay per chunk —
    the watermark is a slow-moving gauge, so samples closer than
    ``min_interval_s`` are skipped. Rare boundaries (a fleet swap)
    pass ``force=True``."""
    global _watermark_last
    ledger = get_ledger()
    if not ledger.enabled:
        return None
    now = time.monotonic()
    if not force:
        with _watermark_lock:
            if now - _watermark_last < min_interval_s:
                return None
            _watermark_last = now
    else:
        with _watermark_lock:
            _watermark_last = now
    value = device_memory_bytes()
    if value is not None:
        ledger.record_watermark(value)
    return value


@contextlib.contextmanager
def nan_guard(enable: bool = True) -> Iterator[None]:
    """Scoped ``jax_debug_nans``: ops that produce NaN re-run op-by-op
    and raise at the source op instead of poisoning the whole rollout.
    Restores the previous setting on exit (compose freely with training
    code that toggles it)."""
    previous = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", previous)
