"""Runtime tracing guards: the dynamic half of graftlint.

The AST linter (linter.py) sees one file at a time; these guards watch
the properties that only exist at run time:

- :class:`RetraceGuard` — counts how many times a jit target is actually
  traced and (optionally) fails the process past a budget. Accidental
  retracing is the #1 silent throughput killer in JAX: a weak-typed
  scalar or a drifting static arg recompiles a multi-second XLA program
  every iteration and nothing crashes.
- :func:`no_host_transfers` — a ``jax.transfer_guard_device_to_host``
  context for the trainer hot loop: any ``.item()`` / ``float()`` /
  implicit ``__array__`` sync inside the guarded region raises instead
  of silently serializing the dispatch pipeline (on a tunneled TPU each
  sync pays a full RTT).
- :func:`nan_guard` — scoped ``jax_debug_nans`` toggle: XLA re-runs any
  op that produced a NaN in op-by-op mode and raises at the source op.

All three are re-exported through ``utils.profiling`` and opt-in from
``train.trainer.TrainConfig`` (``guard_retraces`` / ``guard_transfers``
/ ``guard_nans``).
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any, Callable, Iterator, Optional

import jax


class RetraceError(RuntimeError):
    """A guarded jit target compiled more often than its budget allows."""


class RetraceGuard:
    """Count (and optionally bound) the traces of a jit target.

    Wrap the Python callable BEFORE handing it to ``jax.jit``: the
    wrapper body runs exactly once per trace (jit executes the Python
    function only on cache miss), so ``count`` equals the number of
    compilations this process triggered for it.

    >>> guard = RetraceGuard("train_iteration", max_traces=2)
    >>> step = jax.jit(guard.wrap(step_fn), donate_argnums=(0,))

    ``max_traces=None`` only counts. With a budget, the trace that
    exceeds it raises :class:`RetraceError` naming the argument
    signature that caused it — at the retrace, where the stack still
    shows which caller changed shapes/dtypes.
    """

    def __init__(
        self, name: str = "jit-target", max_traces: Optional[int] = None
    ) -> None:
        self.name = name
        self.max_traces = max_traces
        self._lock = threading.Lock()
        self.count = 0

    def reset(self) -> None:
        with self._lock:
            self.count = 0

    def _describe(self, args: Any, kwargs: Any) -> str:
        def leaf(x: Any) -> str:
            shape = getattr(x, "shape", None)
            dtype = getattr(x, "dtype", None)
            if shape is None or dtype is None:
                return f"{type(x).__name__}:{x!r}"[:40]
            return f"{dtype}{list(shape)}"

        leaves = jax.tree_util.tree_leaves((args, kwargs))
        head = ", ".join(leaf(x) for x in leaves[:8])
        extra = len(leaves) - 8
        return head + (f", … +{extra} leaves" if extra > 0 else "")

    def wrap(self, fn: Callable[..., Any]) -> Callable[..., Any]:
        @functools.wraps(fn)
        def traced(*args: Any, **kwargs: Any) -> Any:
            with self._lock:
                self.count += 1
                count = self.count
            if self.max_traces is not None and count > self.max_traces:
                raise RetraceError(
                    f"{self.name!r} traced {count} times "
                    f"(budget {self.max_traces}) — a shape, dtype, "
                    "weak-type, or static-arg drift is forcing "
                    "recompilation every call; offending signature: "
                    f"[{self._describe(args, kwargs)}]"
                )
            try:
                return fn(*args, **kwargs)
            except Exception:
                # A trace that raises produced no compiled program (and
                # no jit cache entry), so it must not consume budget —
                # otherwise one malformed call poisons the target for
                # every valid caller after it (the serving engine leans
                # on this: budget-1 per bucket must mean one SUCCESSFUL
                # compile, not one attempt).
                with self._lock:
                    self.count -= 1
                raise

        return traced


@contextlib.contextmanager
def no_host_transfers(level: str = "disallow") -> Iterator[None]:
    """Forbid device->host transfers in the wrapped region.

    Device-to-host only: host-to-device constant uploads during
    compilation are part of tracing and stay allowed — the hot-loop
    poison is the reverse direction (``.item()``, ``float()``, implicit
    ``np.asarray``), which serializes the dispatch pipeline behind a
    sync. ``level`` follows ``jax.transfer_guard``: ``"disallow"``
    raises, ``"log"`` prints and continues (triage mode).

    Backend caveat: the XLA CPU backend aliases device and host memory,
    so readbacks there are zero-copy and the guard never fires — it is a
    no-op on CPU and enforceable on TPU/GPU. The static complement
    (graftlint's host-sync-in-jit rule) catches spelled-out syncs on
    every backend; this guard catches the implicit ones on hardware,
    which is where they cost real RTTs.
    """
    with jax.transfer_guard_device_to_host(level):
        yield


@contextlib.contextmanager
def nan_guard(enable: bool = True) -> Iterator[None]:
    """Scoped ``jax_debug_nans``: ops that produce NaN re-run op-by-op
    and raise at the source op instead of poisoning the whole rollout.
    Restores the previous setting on exit (compose freely with training
    code that toggles it)."""
    previous = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", previous)
