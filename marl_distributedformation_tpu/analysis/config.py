"""graftlint configuration: the ``[tool.graftlint]`` pyproject block.

```toml
[tool.graftlint]
exclude = ["compat/sb3_import.py"]        # repo-root-relative path prefixes

[tool.graftlint.severity]
missing-donate = "warn"                   # per-rule: "error" | "warn" | "off"
```

Severities gate the CLI exit code (``--check`` fails on errors only) and
the tier-1 package scan (zero errors AND zero warns — the repo itself
stays clean; downgrades are for downstream users adopting the linter on
a dirty tree).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, Optional, Sequence, Tuple

SEVERITIES = ("error", "warn", "off")


@dataclasses.dataclass(frozen=True)
class GraftlintConfig:
    """Resolved linter configuration."""

    severity: Dict[str, str] = dataclasses.field(default_factory=dict)
    exclude: Tuple[str, ...] = ()

    def rule_severity(self, rule_name: str, default: str) -> str:
        sev = self.severity.get(rule_name, default)
        if sev not in SEVERITIES:
            raise ValueError(
                f"[tool.graftlint] severity for {rule_name!r} must be one "
                f"of {SEVERITIES}, got {sev!r}"
            )
        return sev

    def excludes_path(self, path: Path, root: Optional[Path] = None) -> bool:
        """True when ``path`` falls under an excluded prefix (matched on
        the path relative to ``root`` when given, else on the path as
        spelled)."""
        candidates = [str(path)]
        if root is not None:
            try:
                candidates.append(str(path.resolve().relative_to(root.resolve())))
            except ValueError:
                pass
        for pattern in self.exclude:
            for cand in candidates:
                rel = cand.replace("\\", "/")
                if rel == pattern or rel.startswith(pattern.rstrip("/") + "/"):
                    return True
        return False


def _read_toml(path: Path) -> Optional[dict]:
    """Parse TOML, or None when no parser exists on this interpreter
    (py 3.10 without tomli — tomllib is 3.11+ and tomli only ships with
    the dev extras)."""
    try:
        import tomllib  # py >= 3.11
    except ImportError:
        try:
            import tomli as tomllib
        except ImportError:
            return None
    with open(path, "rb") as f:
        return tomllib.load(f)


def load_config(root: Optional[Path] = None) -> GraftlintConfig:
    """Load ``[tool.graftlint]`` from ``{root}/pyproject.toml`` (repo root
    by default). Absent file or block means all-defaults; so does a
    runtime-only py3.10 install with no TOML parser — every rule then
    runs at its built-in default severity, which for this repo is the
    stricter-or-equal direction (the pyproject block only downgrades)."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    pyproject = Path(root) / "pyproject.toml"
    if not pyproject.is_file():
        return GraftlintConfig()
    parsed = _read_toml(pyproject)
    if parsed is None:
        return GraftlintConfig()
    return config_from_dict(parsed.get("tool", {}).get("graftlint", {}))


def config_from_dict(block: dict) -> GraftlintConfig:
    severity = dict(block.get("severity", {}))
    exclude: Sequence[str] = block.get("exclude", ())
    return GraftlintConfig(severity=severity, exclude=tuple(exclude))
