"""graftlint: JAX-hygiene static analysis + runtime tracing guards.

The silent killers of a compiled-loop JAX stack are exactly the things no
functional test catches: accidental retracing, host<->device transfers
inside the train loop, PRNG key reuse, and version-drifting APIs
(PAPERS.md: Podracer and JaxMARL both attribute their throughput to
keeping the whole loop compiled and device-resident). This subpackage
proves the loop stays that way, permanently, in CI:

- **static** (``linter.py`` + ``rules/``): an AST linter with 8
  JAX-specific rules run over the whole package by ``tests/
  test_graftlint.py`` and ``scripts/graftlint.py --check``;
- **runtime** (``guards.py``): a retrace counter, a device->host
  transfer guard for the trainer hot loop, and a NaN-guard toggle —
  surfaced through ``utils.profiling`` and opt-in from
  ``train.trainer.TrainConfig``.

Rule catalogue, suppression syntax, and guard usage: docs/static_analysis.md.
"""

from marl_distributedformation_tpu.analysis.config import (  # noqa: F401
    GraftlintConfig,
    load_config,
)
from marl_distributedformation_tpu.analysis.guards import (  # noqa: F401
    RetraceError,
    RetraceGuard,
    nan_guard,
    no_host_transfers,
)
from marl_distributedformation_tpu.analysis.linter import (  # noqa: F401
    Violation,
    lint_paths,
    lint_source,
)
