"""AST linter engine: traced-scope discovery, taint tracking, suppression.

The rules (``analysis/rules/``) are small because this module answers the
two questions every JAX-hygiene check needs:

1. **Which functions are traced?** Anything decorated with / passed to a
   tracing entry point (``jit``, ``shard_map``, ``vmap``, ``pmap``,
   ``lax.scan`` / ``while_loop`` / ``cond`` / ``fori_loop`` / ``map``),
   plus every function *nested inside* one (closures trace with their
   parent). Cross-module tracing (a function returned here and jitted
   elsewhere) is invisible to a per-file AST pass — the linter covers the
   jit boundary layer and the runtime guards (guards.py) cover the rest.
2. **Which names hold traced values?** Parameters of traced scopes, plus
   anything assigned from an expression that mentions a tainted name —
   EXCEPT static extractors (``x.shape``, ``x.ndim``, ``x.dtype``,
   ``len(x)``, ``isinstance(...)``, ``x is None``), which produce
   trace-time Python values and must not poison downstream checks.

Suppression: ``# graftlint: disable=<rule>[,<rule>...]`` as a trailing
comment on the flagged line or as a comment-only line directly above it;
``# graftlint: disable-file=<rule>`` anywhere disables a rule for the
whole file.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from marl_distributedformation_tpu.analysis.config import GraftlintConfig

# Attribute / builtin accesses that yield static (non-traced) Python values
# even when applied to a traced array.
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "aval", "weak_type"})
# Parameter names that conventionally carry static config objects in this
# codebase (EnvParams / PPOConfig / TrainConfig dataclasses, meshes), not
# traced arrays — tuned so `if params.strict_parity:` style trace-time
# branching stays clean. NN parameters are spelled `nn_params` /
# `train_state.params` here, so `params` is unambiguous. A tuned list is
# the standard lint trade-off; adjust here if the convention changes.
STATIC_PARAM_NAMES = frozenset(
    {"self", "cls", "params", "config", "cfg", "ppo", "env_params",
     "hparams", "mesh", "train_config"}
)
STATIC_CALLS = frozenset(
    {"len", "isinstance", "issubclass", "getattr", "hasattr", "type", "id",
     "callable", "repr", "str"}
)

# Tracing entry points -> positions of the traced callables among the
# positional args. Decorator usage is handled separately.
TRACING_ENTRY_ARGS: Dict[str, Tuple[int, ...]] = {
    "jax.jit": (0,),
    "jit": (0,),
    "jax.shard_map": (0,),
    "shard_map": (0,),
    "jax.vmap": (0,),
    "vmap": (0,),
    "jax.pmap": (0,),
    "pmap": (0,),
    "jax.lax.scan": (0,),
    "lax.scan": (0,),
    "jax.lax.map": (0,),
    "lax.map": (0,),
    "jax.lax.while_loop": (0, 1),
    "lax.while_loop": (0, 1),
    "jax.lax.cond": (1, 2),
    "lax.cond": (1, 2),
    "jax.lax.fori_loop": (2,),
    "lax.fori_loop": (2,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
}

JIT_NAMES = frozenset({"jax.jit", "jit"})
PARTIAL_NAMES = frozenset({"functools.partial", "partial"})

_DISABLE_RE = re.compile(r"#\s*graftlint:\s*disable\s*=\s*([\w\-,\s]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*graftlint:\s*disable-file\s*=\s*([\w\-,\s]+)")


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _split_rule_list(raw: str) -> Set[str]:
    """Leading REGISTERED rule names from a suppression payload. The
    payload ends at the first token that is not a known rule, so trailing
    prose can mention other rules by name without suppressing them
    (``disable=numpy-in-jit unlike host-sync-in-jit this is safe``
    suppresses only numpy-in-jit)."""
    from marl_distributedformation_tpu.analysis.rules import rule_names

    known = set(rule_names())
    names: Set[str] = set()
    for token in re.split(r"[\s,]+", raw.strip()):
        if token in known:
            names.add(token)
        else:
            break
    return names


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: str  # "error" | "warn"

    def __str__(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.severity.upper()} [{self.rule}] {self.message}"
        )


class Rule:
    """Base class for graftlint rules. Subclasses set ``name``,
    ``default_severity``, ``description`` and implement :meth:`check`."""

    name: str = "abstract"
    default_severity: str = "error"
    description: str = ""

    def check(self, ctx: "ModuleContext") -> Iterator[Tuple[int, int, str]]:
        raise NotImplementedError


FunctionLike = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class ModuleContext:
    """One parsed module plus the traced-scope / taint analyses rules
    share. Built once per file; rules only read from it."""

    def __init__(self, tree: ast.Module, source: str, path: str) -> None:
        self.tree = tree
        self.path = path
        self.lines = source.splitlines()
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self._defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs_by_name.setdefault(node.name, []).append(node)
        self.traced_scopes: Set[ast.AST] = self._find_traced_scopes()
        self.traced_roots: List[ast.AST] = [
            scope
            for scope in self.traced_scopes
            if not self._has_traced_ancestor(scope)
        ]
        self.traced_roots.sort(key=lambda n: (n.lineno, n.col_offset))
        self._taint_cache: Dict[ast.AST, Set[str]] = {}
        self.file_disabled: Set[str] = set()
        for line in self.lines:
            m = _DISABLE_FILE_RE.search(line)
            if m:
                self.file_disabled |= _split_rule_list(m.group(1))

    # -- traced-scope discovery ----------------------------------------

    def _is_jit_like(self, node: ast.AST) -> bool:
        """True for an expression denoting a tracing transform: ``jax.jit``,
        ``shard_map``, ``functools.partial(jax.jit, ...)``, or a call of
        any of those (``jax.jit(static_argnums=...)`` decorator style)."""
        name = dotted_name(node)
        if name in TRACING_ENTRY_ARGS:
            return True
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname in TRACING_ENTRY_ARGS:
                return True
            if fname in PARTIAL_NAMES and node.args:
                return self._is_jit_like(node.args[0])
        return False

    def _resolve_callable(self, node: ast.AST) -> List[ast.AST]:
        if isinstance(node, ast.Lambda):
            return [node]
        if isinstance(node, ast.Name):
            return list(self._defs_by_name.get(node.id, ()))
        if isinstance(node, ast.Call):
            # peel wrapping transforms: jax.jit(jax.vmap(f)), partial(f, ...)
            fname = dotted_name(node.func)
            if fname in TRACING_ENTRY_ARGS or fname in PARTIAL_NAMES:
                return [
                    t for arg in node.args for t in self._resolve_callable(arg)
                ]
        return []

    def _find_traced_scopes(self) -> Set[ast.AST]:
        traced: Set[ast.AST] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(self._is_jit_like(d) for d in node.decorator_list):
                    traced.add(node)
            elif isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                positions = TRACING_ENTRY_ARGS.get(fname or "")
                if positions is None:
                    continue
                for pos in positions:
                    if pos < len(node.args):
                        traced.update(self._resolve_callable(node.args[pos]))
        # Closure rule: every function nested in a traced scope traces
        # with it.
        out = set(traced)
        for node in ast.walk(self.tree):
            if isinstance(node, FunctionLike) and any(
                anc in traced for anc in self._ancestors(node)
            ):
                out.add(node)
        return out

    def _ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def _has_traced_ancestor(self, node: ast.AST) -> bool:
        return any(a in self.traced_scopes for a in self._ancestors(node))

    def enclosing_traced_scope(self, node: ast.AST) -> Optional[ast.AST]:
        if node in self.traced_scopes:
            return node
        for anc in self._ancestors(node):
            if anc in self.traced_scopes:
                return anc
        return None

    # -- taint ----------------------------------------------------------

    @staticmethod
    def _param_names(scope: ast.AST) -> Set[str]:
        """Parameters presumed to carry traced values: everything except
        config-named params (STATIC_PARAM_NAMES) and flag-like params
        whose default is a literal constant (``with_obs=True``,
        ``block_r=1024`` — static mode switches / tile sizes, which under
        jit are static_argnums or closure constants)."""
        args = scope.args
        positional = [*args.posonlyargs, *args.args]
        static: Set[str] = set(STATIC_PARAM_NAMES)
        for arg, default in zip(
            reversed(positional), reversed(args.defaults)
        ):
            if isinstance(default, ast.Constant):
                static.add(arg.arg)
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None and isinstance(default, ast.Constant):
                static.add(arg.arg)
        names = {
            a.arg
            for a in (
                *positional, *args.kwonlyargs,
                *filter(None, (args.vararg, args.kwarg)),
            )
        }
        return names - static

    def taint_for(self, root: ast.AST) -> Set[str]:
        """Names holding (potentially) traced values anywhere inside the
        traced root: its parameters, parameters of nested functions, and
        fixpoint propagation through assignments."""
        cached = self._taint_cache.get(root)
        if cached is not None:
            return cached
        taint: Set[str] = set()
        for node in ast.walk(root):
            if isinstance(node, FunctionLike):
                taint |= self._param_names(node)
        if isinstance(root, FunctionLike):
            taint |= self._param_names(root)
        for _ in range(4):  # fixpoint; chains deeper than 4 hops are rare
            grew = False
            for node in ast.walk(root):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.For):
                    targets, value = [node.target], node.iter
                if value is None or not self.expr_tainted(value, taint):
                    continue
                for name in self._target_names(targets):
                    if name not in taint:
                        taint.add(name)
                        grew = True
            if not grew:
                break
        self._taint_cache[root] = taint
        return taint

    @staticmethod
    def _target_names(targets: Iterable[ast.AST]) -> Iterator[str]:
        for t in targets:
            for node in ast.walk(t):
                if isinstance(node, ast.Name):
                    yield node.id

    def expr_tainted(self, node: ast.AST, taint: Set[str]) -> bool:
        """Does evaluating ``node`` touch a traced value? Static
        extractors (shape/dtype/len/isinstance/is-None) break the chain."""
        if isinstance(node, ast.Name):
            return node.id in taint
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value, taint)
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname in STATIC_CALLS:
                return False
            return any(
                self.expr_tainted(c, taint)
                for c in ast.iter_child_nodes(node)
            )
        if isinstance(node, ast.Compare):
            if all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                return False  # `x is None`: structural, never traced
            if any(
                isinstance(c, ast.Constant) and isinstance(c.value, str)
                for c in (node.left, *node.comparators)
            ):
                return False  # comparing to a string: trace-time metadata
        return any(
            self.expr_tainted(c, taint) for c in ast.iter_child_nodes(node)
        )

    # -- suppression -----------------------------------------------------

    def suppressed(self, line: int, rule_name: str) -> bool:
        if rule_name in self.file_disabled:
            return True
        candidates = []
        if 1 <= line <= len(self.lines):
            candidates.append(self.lines[line - 1])
        if 2 <= line <= len(self.lines) + 1:
            above = self.lines[line - 2]
            if above.lstrip().startswith("#"):
                candidates.append(above)
        for text in candidates:
            m = _DISABLE_RE.search(text)
            if m and rule_name in _split_rule_list(m.group(1)):
                return True
        return False


# ----------------------------------------------------------------------
# Entry points
# ----------------------------------------------------------------------


def lint_source(
    source: str,
    path: str = "<string>",
    config: Optional[GraftlintConfig] = None,
    rules: Optional[Sequence[Rule]] = None,
) -> List[Violation]:
    """Lint one module's source; returns violations sorted by location.
    Rules configured ``off`` are skipped; per-line / per-file suppression
    comments are honored."""
    from marl_distributedformation_tpu.analysis.rules import all_rules

    config = config or GraftlintConfig()
    active = list(rules) if rules is not None else all_rules()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [
            Violation(
                "syntax-error", path, e.lineno or 0, e.offset or 0,
                f"file does not parse: {e.msg}", "error",
            )
        ]
    ctx = ModuleContext(tree, source, path)
    violations: List[Violation] = []
    for rule in active:
        severity = config.rule_severity(rule.name, rule.default_severity)
        if severity == "off":
            continue
        for line, col, message in rule.check(ctx):
            if ctx.suppressed(line, rule.name):
                continue
            violations.append(
                Violation(rule.name, path, line, col, message, severity)
            )
    return sorted(violations, key=lambda v: (v.path, v.line, v.col, v.rule))


def iter_python_files(
    paths: Sequence, config: GraftlintConfig, root: Optional[Path] = None
) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not config.excludes_path(f, root):
                    yield f
        elif p.suffix == ".py" and not config.excludes_path(p, root):
            yield p


def lint_paths(
    paths: Sequence,
    config: Optional[GraftlintConfig] = None,
    rules: Optional[Sequence[Rule]] = None,
    root: Optional[Path] = None,
) -> List[Violation]:
    """Lint every ``.py`` file under ``paths`` (files or directories),
    honoring the config's exclude list."""
    config = config or GraftlintConfig()
    violations: List[Violation] = []
    for f in iter_python_files(paths, config, root):
        violations.extend(
            lint_source(
                f.read_text(encoding="utf-8"), str(f), config, rules
            )
        )
    return violations
