"""Version-portability shims for drifting JAX APIs.

JAX moved ``shard_map`` from ``jax.experimental.shard_map.shard_map``
(<= 0.4.x) to ``jax.shard_map`` (>= 0.6), and renamed its replication
checker from ``check_rep`` to ``check_vma`` in the same move. Every
``shard_map`` call site in this package routes through :func:`shard_map`
below so the package runs unmodified on either side of the drift; the
``graftlint`` ``deprecated-api`` rule (analysis/rules/deprecated.py)
enforces that no new direct spelling sneaks back in.

Also home to :func:`manual_axis_context`, the trace-context probe that
``ops.knn._spmd_partitioner_controlled`` uses on pre-sharding-in-types
JAX (where tracer avals carry no sharding): inside ``shard_map`` the mesh
axes are bound as named axis frames, under plain ``jit`` they are not —
the same boundary the newer aval-mesh ``axis_types`` probe detects.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax


def _ensure_sharding_invariant_prng() -> None:
    """Normalize the PRNG to modern-JAX semantics: sharding-invariant.

    jax <= 0.4.x defaults ``jax_threefry_partitionable`` to False, where
    a ``jax.random`` draw lowered under the SPMD partitioner (sharded
    operands in the surrounding program) produces DIFFERENT bits than
    the identical unsharded program — measured here as a 6% reward
    divergence between dp×sp-sharded and single-device training with
    identical seeds, silently breaking the repo's sharded == unsharded
    trajectory invariant (tests/test_parallel.py). Newer JAX made
    partitionable threefry the default and removed the flag; force it on
    wherever the flag still exists so every JAX version draws the same,
    placement-independent streams.
    """
    try:
        if not jax.config.jax_threefry_partitionable:
            jax.config.update("jax_threefry_partitionable", True)
    except AttributeError:
        pass  # new jax: partitionable is the only implementation


_ensure_sharding_invariant_prng()


def resolve_shard_map() -> tuple[Callable[..., Any], bool]:
    """The installed JAX's shard_map and whether it is the NEW spelling:
    ``(jax.shard_map, True)`` when present, else
    ``(jax.experimental.shard_map.shard_map, False)``. Resolved at call
    time (not import time) so tests can monkeypatch either spelling."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, True
    # graftlint: disable=deprecated-api — this IS the shim the rule points to
    from jax.experimental.shard_map import shard_map as legacy

    return legacy, False


def shard_map(
    f: Callable[..., Any],
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    check_vma: Optional[bool] = None,
) -> Callable[..., Any]:
    """``shard_map`` across JAX versions (keyword-only, new-API surface).

    ``check_vma`` maps onto the installed API's replication-checker flag:
    passed through verbatim on new JAX, translated to ``check_rep`` on
    old JAX; ``None`` leaves the installed default in place.
    """
    impl, is_new = resolve_shard_map()
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_vma is not None:
        kwargs["check_vma" if is_new else "check_rep"] = check_vma
    return impl(f, **kwargs)


def manual_axis_context() -> bool:
    """True when the caller is tracing inside a manual-axes region
    (``shard_map`` / ``pmap``) on pre-sharding-in-types JAX, where the
    mesh axes are bound as named axis frames. False under plain ``jit``
    or eager execution, and on JAX versions that removed the axis-env
    accessor (those carry sharding on tracer avals instead — see
    ``ops.knn._spmd_partitioner_controlled``)."""
    for probe in (
        lambda: jax.core.get_axis_env().axis_sizes,
        # jax.core re-exports get_axis_env on some 0.4.x releases only;
        # the _src accessor covers most of the legacy range, and the
        # thread-local axis frames the releases before get_axis_env.
        lambda: jax._src.core.get_axis_env().axis_sizes,
        lambda: {
            f.name: f.size
            for f in jax.core.thread_local_state.trace_state.axis_env
        },
    ):
        try:
            sizes = probe()
        except Exception:
            continue
        return bool(sizes)
    return False
