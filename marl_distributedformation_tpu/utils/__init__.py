"""Config, logging, checkpointing, and profiling utilities."""

from marl_distributedformation_tpu.utils.config import (  # noqa: F401
    Config,
    apply_overrides,
    env_params_from_config,
    load_config,
    repo_root,
    scenario_schedule_from_config,
    setup_platform,
    validate_override_keys,
)
from marl_distributedformation_tpu.utils.checkpoint import (  # noqa: F401
    AsyncCheckpointWriter,
    CheckpointDiscovery,
    CorruptCheckpointError,
    broadcast_restore,
    checkpoint_path,
    checkpoint_step,
    device_snapshot,
    NonFiniteCheckpointError,
    latest_checkpoint,
    latest_sweep_state,
    msgpack_restore_file,
    own_restored,
    prune_checkpoints,
    quarantine_checkpoint,
    read_checkpoint_payload,
    restore_checkpoint,
    restore_checkpoint_partial,
    restore_latest_partial,
    save_checkpoint,
    save_sweep_state,
    sweep_state_path,
)
from marl_distributedformation_tpu.utils.logging import MetricsLogger  # noqa: F401
from marl_distributedformation_tpu.utils.profiling import (  # noqa: F401
    Throughput,
    trace,
)
