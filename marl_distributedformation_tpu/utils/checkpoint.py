"""Checkpoint save/restore with the reference's discovery contract.

Write path mirrors SB3's ``CheckpointCallback`` naming
(``rl_model_{num_timesteps}_steps`` under ``logs/{name}/``,
vectorized_env.py:124); read path mirrors ``visualize_policy.py:31`` — pick
the file whose step number (``name.split("_")[-2]``) is largest. Unlike the
reference (which never resumes optimizer state — SURVEY.md §5), checkpoints
here carry params, optimizer state, and PRNG key, so training resume is
exact.

Format: flax msgpack serialization of the train-state pytree in a single
file — host-side, TPU-independent, and restorable on any backend.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Any, Optional

from flax import serialization

_STEP_RE = re.compile(r"rl_model_(\d+)_steps")


def checkpoint_path(log_dir: str | Path, num_timesteps: int) -> Path:
    return Path(log_dir) / f"rl_model_{num_timesteps}_steps.msgpack"


def save_checkpoint(
    log_dir: str | Path, num_timesteps: int, target: Any
) -> Path:
    """Serialize ``target`` (any pytree) to ``rl_model_{steps}_steps.msgpack``."""
    path = checkpoint_path(log_dir, num_timesteps)
    path.parent.mkdir(parents=True, exist_ok=True)
    # Dot-prefixed temp name so a torn write can never be picked up by
    # latest_checkpoint (which also filters on the .msgpack suffix).
    tmp = path.parent / f".{path.name}.tmp"
    tmp.write_bytes(serialization.to_bytes(target))
    tmp.replace(path)  # atomic: no torn checkpoints on crash (SURVEY.md §5)
    return path


def latest_checkpoint(log_dir: str | Path) -> Optional[Path]:
    """Find the checkpoint with the largest step number, exactly like the
    reference's discovery scan (visualize_policy.py:29-32)."""
    log_dir = Path(log_dir)
    if not log_dir.is_dir():
        return None
    candidates = [
        p
        for p in log_dir.iterdir()
        if p.suffix == ".msgpack" and _STEP_RE.search(p.name)
    ]
    if not candidates:
        return None
    return max(candidates, key=lambda p: int(_STEP_RE.search(p.name).group(1)))


def restore_checkpoint(path: str | Path, template: Any) -> Any:
    """Restore a pytree serialized by ``save_checkpoint`` into the structure
    of ``template`` (same-treedef pytree with correctly-shaped leaves)."""
    return serialization.from_bytes(template, Path(path).read_bytes())


def checkpoint_step(path: str | Path) -> int:
    m = _STEP_RE.search(Path(path).name)
    if not m:
        raise ValueError(f"not a checkpoint path: {path}")
    return int(m.group(1))
