"""Checkpoint save/restore with the reference's discovery contract.

Write path mirrors SB3's ``CheckpointCallback`` naming
(``rl_model_{num_timesteps}_steps`` under ``logs/{name}/``,
vectorized_env.py:124); read path mirrors ``visualize_policy.py:31`` — pick
the file whose step number (``name.split("_")[-2]``) is largest. Unlike the
reference (which never resumes optimizer state — SURVEY.md §5), checkpoints
here carry params, optimizer state, and PRNG key, so training resume is
exact.

Format: flax msgpack serialization of the train-state pytree in a single
file — host-side, TPU-independent, and restorable on any backend.
"""

from __future__ import annotations

import json
import os
import random
import re
import struct
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional

from flax import serialization

from marl_distributedformation_tpu.chaos.plane import (
    SimulatedCrash,
    fault_point,
)

_STEP_RE = re.compile(r"rl_model_(\d+)_steps")
# Population-sweep state files live beside member dirs under the sweep's
# log_dir; the distinct prefix keeps them invisible to the rl_model_*
# discovery scan (visualize_policy/member resume must never pick one up).
_SWEEP_STEP_RE = re.compile(r"sweep_state_(\d+)_steps")


def checkpoint_path(log_dir: str | Path, num_timesteps: int) -> Path:
    return Path(log_dir) / f"rl_model_{num_timesteps}_steps.msgpack"


def sweep_state_path(log_dir: str | Path, num_timesteps: int) -> Path:
    return Path(log_dir) / f"sweep_state_{num_timesteps}_steps.msgpack"


def save_checkpoint(
    log_dir: str | Path, num_timesteps: int, target: Any, sync: bool = True
) -> Optional[Path]:
    """Serialize ``target`` (any pytree) to ``rl_model_{steps}_steps.msgpack``.

    Multi-host: only the coordinator process writes; it returns the path and
    every other process returns **None** (the file does not exist on their
    disks). A ``sync_global_devices`` barrier after the write guarantees
    that when any process returns, the coordinator's file is durable — a
    host may immediately hand the path to a reader. Leaves must be
    process-addressable on the coordinator — replicated trees (params/opt
    state) always are; cross-host-sharded state must be excluded by the
    caller (as ``Trainer._checkpoint_target`` does for the dp-sharded env
    state).
    """
    import jax

    from marl_distributedformation_tpu.parallel.distributed import (
        is_coordinator,
    )

    path = checkpoint_path(log_dir, num_timesteps)
    on_coordinator = is_coordinator()
    if on_coordinator:
        try:
            _write_atomic(path, target)
        except NonFiniteCheckpointError as e:
            # Degrade, never die — and never skip the durability barrier
            # below (peers must not hang on a coordinator that refused a
            # poisoned write).
            _audit_nonfinite_skip(path, str(e))
            path = None
    if sync and jax.process_count() > 1:
        # ``sync=False`` lets a caller writing MANY files per logical
        # checkpoint (the sweep's per-member loop) batch the durability
        # barrier into one trailing synced write instead of paying a
        # cross-host round trip per file.
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_{num_timesteps}")
    return path if on_coordinator else None


# ----------------------------------------------------------------------
# Crash-consistent format: payload + checksum footer
# ----------------------------------------------------------------------
#
# The rename-is-publication protocol makes a torn WRITE invisible, but
# it cannot see silent media damage or a truncation that happens after
# the rename (a crashed fsync-less host, a bad sector, an injected
# bit-flip in a chaos campaign). Every checkpoint therefore carries a
# 20-byte footer: crc32(payload) + payload length + magic, validated on
# every read. Footer-less files (pre-chaos-plane checkpoints, foreign
# msgpack files) read as legacy payloads unchanged, so THIS reader
# handles both formats. The converse does not hold: a plain
# ``msgpack_restore(read_bytes())`` from a pre-footer release chokes on
# the trailing 20 bytes — rolling the READER back past this change
# while a new trainer keeps writing is the one unsupported direction
# (roll the writer back too, or strip footers with
# read_checkpoint_payload first).

_CKPT_MAGIC = b"MARLCKPT"
_FOOTER = struct.Struct("<Iq8s")  # crc32, payload length, magic


class CorruptCheckpointError(ValueError):
    """A checkpoint whose bytes fail validation (checksum mismatch,
    truncation past the footer, undecodable msgpack) — damage, not an
    architecture mismatch."""


class NonFiniteCheckpointError(ValueError):
    """A checkpoint target carrying NaN/Inf float leaves. The write gate
    (:func:`_write_atomic`) refuses to publish these: a diverged trainer
    must never make a poisoned state visible to ``latest_checkpoint`` /
    ``CheckpointDiscovery`` — the gate would reject it one candidate at
    a time, resume would restore the divergence, and the recovery
    ladder's rollback walk would find poison where it needs a last-good
    state (train/recovery.py, docs/recovery.md). Callers degrade:
    the async writer skips-with-audit, ``save_checkpoint`` returns
    None."""


def _with_footer(payload: bytes) -> bytes:
    return payload + _FOOTER.pack(
        zlib.crc32(payload) & 0xFFFFFFFF, len(payload), _CKPT_MAGIC
    )


def _strip_footer(data: bytes, origin: str) -> bytes:
    """Validate + strip the checksum footer; legacy (footer-less) bytes
    pass through whole. Raises :class:`CorruptCheckpointError` on a
    failed check."""
    if len(data) < _FOOTER.size or data[-8:] != _CKPT_MAGIC:
        return data  # legacy file: no footer to validate
    crc, length, _ = _FOOTER.unpack(data[-_FOOTER.size:])
    payload = data[: -_FOOTER.size]
    if length != len(payload):
        raise CorruptCheckpointError(
            f"checkpoint {origin}: footer says {length} payload bytes "
            f"but {len(payload)} are present (truncated write?)"
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CorruptCheckpointError(
            f"checkpoint {origin}: payload checksum mismatch "
            "(bit rot or torn write)"
        )
    return payload


def quarantine_checkpoint(path: str | Path, reason: str) -> Optional[Path]:
    """Move a corrupt checkpoint ASIDE instead of leaving it to wedge
    every future resume/reload: renamed to ``{name}.quarantined`` (the
    suffix is no longer ``.msgpack``, so ``latest_checkpoint`` and
    ``CheckpointDiscovery`` can never serve it), audit-logged to
    ``quarantine.jsonl`` beside it, counted and flight-recorded.
    Best-effort — returns the quarantine path or None; never raises
    (quarantine runs on already-failing paths)."""
    from marl_distributedformation_tpu.obs import get_registry, get_tracer

    path = Path(path)
    target = path.with_name(path.name + ".quarantined")
    try:
        path.replace(target)
    except OSError:
        target = None
    try:
        with open(path.parent / "quarantine.jsonl", "a") as f:
            f.write(json.dumps({
                "time": round(time.time(), 3),
                "file": path.name,
                "quarantined_as": target.name if target else None,
                "reason": str(reason)[:300],
            }) + "\n")
    except OSError:
        pass
    get_registry().counter("checkpoint_quarantined_total").inc()
    get_tracer().incident(
        "checkpoint_quarantined", path=str(path), reason=str(reason)[:300]
    )
    return target


def read_checkpoint_payload(
    path: str | Path, quarantine: bool = True
) -> bytes:
    """Checkpoint bytes with the checksum footer validated and
    stripped. A failed check quarantines the file (unless told not to)
    and raises :class:`CorruptCheckpointError` — corruption is detected
    HERE, at read time, never as a wedged restore downstream."""
    path = Path(path)
    data = path.read_bytes()
    try:
        return _strip_footer(data, origin=str(path))
    except CorruptCheckpointError as e:
        if quarantine:
            quarantine_checkpoint(path, str(e))
        raise


def msgpack_restore_file(path: str | Path, quarantine: bool = True) -> Any:
    """``msgpack_restore`` over a footer-validated checkpoint file —
    THE way to read raw checkpoint state (every reader shares the
    validation + quarantine policy). Undecodable msgpack is corruption
    too (a legacy-format truncation has no footer to fail)."""
    payload = read_checkpoint_payload(path, quarantine=quarantine)
    try:
        return serialization.msgpack_restore(payload)
    except Exception as e:  # noqa: BLE001 — any decode failure is damage
        err = CorruptCheckpointError(
            f"checkpoint {path}: undecodable msgpack payload: {e!r}"
        )
        if quarantine:
            quarantine_checkpoint(path, str(err))
        raise err from e


def nonfinite_leaf(target: Any) -> Optional[str]:
    """Path of the first float leaf carrying NaN/Inf, or None when the
    whole (host-side) tree is finite. The walk costs one pass over the
    bytes — the same order as the crc32 the footer already pays. THE
    one definition of the check — the write gate below, the chaos
    invariant checker, and the trainer's run-end finiteness guarantee
    all share it, so leaf-skipping and dtype rules can never drift."""
    import jax
    import numpy as np

    for path, leaf in jax.tree_util.tree_flatten_with_path(target)[0]:
        if isinstance(leaf, str) or leaf is None:
            continue
        try:
            arr = np.asarray(leaf)
        except (TypeError, ValueError):
            continue  # non-numeric leaf (provenance metadata)
        if np.issubdtype(arr.dtype, np.floating) and (
            not np.isfinite(arr).all()
        ):
            return jax.tree_util.keystr(path)
    return None


def _audit_nonfinite_skip(path: Path, leaf: str) -> None:
    """Counter + flight record for a write the non-finite gate refused —
    a skipped checkpoint is a degradation, never silent."""
    from marl_distributedformation_tpu.obs import get_registry, get_tracer

    get_registry().counter("checkpoint_nonfinite_skipped_total").inc()
    get_tracer().incident(
        "checkpoint_nonfinite_skipped", path=str(path), leaf=leaf
    )


def _write_atomic(
    path: Path, target: Any, check_finite: bool = True
) -> None:
    import jax

    path.parent.mkdir(parents=True, exist_ok=True)
    # Dot-prefixed temp name so a torn write can never be picked up by
    # latest_checkpoint (which also filters on the .msgpack suffix).
    tmp = path.parent / f".{path.name}.tmp"
    # Pull the whole tree in ONE batched transfer before serializing:
    # to_bytes converts leaf-by-leaf, and on a tunneled TPU ~40 separate
    # device->host round-trips can dominate the training loop (the
    # reference-parity save_freq checkpoints every iteration).
    target = jax.device_get(target)
    # The non-finite write gate: a poisoned state must never become
    # discoverable (the train-lane invariant chaos_storm --train pins).
    # ``check_finite=False`` is for harnesses that deliberately forge a
    # diverged file (the pipeline e2e's gate-sabotage fixture) — every
    # production writer keeps the gate on.
    bad = nonfinite_leaf(target) if check_finite else None
    if bad is not None:
        raise NonFiniteCheckpointError(
            f"checkpoint {path.name}: leaf {bad} carries non-finite "
            "values — refusing to publish a diverged state (the async "
            "writer skips-with-audit; the recovery ladder owns the "
            "rollback)"
        )
    fault_point("checkpoint.write", path=tmp)
    tmp.write_bytes(_with_footer(serialization.to_bytes(target)))
    fault_point("checkpoint.pre_rename", path=tmp)
    tmp.replace(path)  # atomic: no torn checkpoints (SURVEY.md §5)
    fault_point("checkpoint.post_rename", path=path)


def own_restored(tree: Any) -> Any:
    """Copy every array leaf of a freshly-restored checkpoint tree into
    a JAX-owned buffer before handing it to a training loop.

    ``msgpack_restore`` returns numpy arrays that can VIEW the decoded
    checkpoint byte buffer, and the training jits DONATE their state
    inputs. On the zero-copy CPU backend a donated input buffer can
    alias that foreign memory — once the restore scope drops the bytes,
    the donated buffer is a use-after-free that later host allocations
    (the async writer serializing the next checkpoint was the observed
    scribbler) corrupt silently: a resumed fused-sweep run produced
    garbage params leaves while every intermediate comparison looked
    clean (tests/test_fused_sweep.py pins the fixed behavior). One
    explicit owning copy per leaf at restore time closes the hazard on
    every backend; non-array leaves (step counters, name strings) pass
    through untouched.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def leaf(x: Any) -> Any:
        if isinstance(x, (np.ndarray, jax.Array)):
            return jnp.array(np.asarray(x))
        return x

    return jax.tree_util.tree_map(leaf, tree)


def device_snapshot(target: Any) -> Any:
    """Device-side copy of every array leaf of a checkpoint target.

    The fused-scan trainer donates its state buffers to the next chunk's
    dispatch; handing the LIVE tree to a background writer would race the
    donation (the writer's ``device_get`` would read deleted buffers).
    ``jnp.copy`` enqueues one async device copy per leaf *behind* the
    program that produces the state — the copies are data-dependent on it
    and independent of everything after, so the next chunk can donate and
    overwrite the originals while the writer drains the snapshot. Host
    leaves (step counters, name strings) pass through untouched.
    """
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, target
    )


class AsyncCheckpointWriter:
    """Background checkpoint pipeline: ``device_get`` + atomic write on a
    writer thread, so a training loop's ``save`` costs one async device
    copy (:func:`device_snapshot`) instead of a synchronous serialize.

    At most ONE write is in flight — ``submit`` joins the previous write
    first, which bounds snapshot memory to one checkpoint and keeps the
    on-disk step order monotonic. The torn-write invariant is
    :func:`_write_atomic`'s — a crash at any point leaves only a
    dot-prefixed ``.tmp`` file that :func:`latest_checkpoint` can never
    pick up.

    **IO failures degrade, they never kill training.** A full disk
    (ENOSPC), a flaky mount, or an injected crash used to surface as
    ``RuntimeError`` on the next ``submit`` — which turned one missed
    checkpoint into a dead always-learning run. Now an ``OSError`` gets
    ``io_retries`` bounded jittered retries (the write callable is
    idempotent: tmp + rename), and an exhausted budget — or a
    :class:`~..chaos.plane.SimulatedCrash` kill of the write — is
    SKIPPED with a full audit trail (``checkpoint_writes_skipped_total``,
    a ``checkpoint_write_skipped`` flight record) while training
    continues; the next save_freq boundary writes the next checkpoint.
    Non-IO failures (a serialization bug, a bad snapshot) still surface
    as ``RuntimeError`` on the next ``submit``/``close`` — those are
    program errors, not weather.
    """

    def __init__(
        self,
        io_retries: int = 3,
        io_backoff_s: float = 0.05,
        rng: Optional[random.Random] = None,
        keep_last_n: int = 0,
        protect: Any = None,
    ) -> None:
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.io_retries = max(0, int(io_retries))
        self.io_backoff_s = float(io_backoff_s)
        self.writes_skipped = 0
        self._rng = rng if rng is not None else random.Random()
        # Retention ring (docs/recovery.md): after every successful
        # ``submit`` write, keep only the newest ``keep_last_n``
        # rl_model_* checkpoints in that file's directory (0 = keep
        # everything, the legacy behavior). ``protect`` is a zero-arg
        # callable returning paths that must survive pruning no matter
        # their age — the trainer passes its last-good rollback target.
        self.keep_last_n = max(0, int(keep_last_n))
        self._protect = protect

    def submit(
        self, path: str | Path, target: Any, on_done: Any = None
    ) -> Path:
        """Queue one atomic write of ``target`` to ``path``. ``target``
        must already be safe to read from another thread (host arrays, or
        a :func:`device_snapshot` the caller's donation cannot touch).
        ``on_done(path)``, if given, runs on the writer thread AFTER the
        rename lands — i.e. when the file is durably discoverable. The
        always-learning pipeline uses it to nudge its checkpoint stream
        the moment a candidate exists instead of waiting out a poll
        interval; a hook failure surfaces like a write failure (next
        submit/close), never silently."""
        path = Path(path)

        def write() -> None:
            _write_atomic(path, target)
            if on_done is not None:
                on_done(path)
            if self.keep_last_n > 0:
                prune_checkpoints(
                    path.parent,
                    self.keep_last_n,
                    protect=(
                        self._protect() if self._protect is not None else ()
                    ),
                )

        self.submit_write(write)
        return path

    def submit_write(self, write_fn: Any) -> None:
        """Queue an arbitrary checkpoint-writing callable on the writer
        thread — the population sweeps use this to land a whole logical
        checkpoint (per-member files + the ``sweep_state`` anchor) as one
        single-flight unit. ``write_fn`` must only touch state that is
        safe to read off-thread (host arrays / a :func:`device_snapshot`)
        and must keep :func:`_write_atomic`'s torn-write invariant for
        every file it produces. Same pipeline contract as :meth:`submit`:
        one write in flight, errors surface on the next submit/close."""
        from marl_distributedformation_tpu.obs.metrics import get_registry

        fault_point("ckpt_writer.submit")
        self.wait()
        # Live-metrics plane: single-flight writer, so depth is 0 or 1 —
        # a depth stuck at 1 means training outruns checkpoint IO.
        get_registry().gauge("checkpoint_queue_depth").set(1.0)
        thread = threading.Thread(
            target=self._run, args=(write_fn,),
            daemon=True, name="ckpt-writer",
        )
        self._thread = thread
        thread.start()

    def _run(self, write_fn: Any) -> None:
        from marl_distributedformation_tpu.obs.metrics import get_registry

        t0 = time.perf_counter()
        try:
            attempt = 0
            while True:
                try:
                    write_fn()
                    break
                except OSError as e:
                    # Disk weather (ENOSPC, a flaky mount): bounded
                    # jittered retries — write_fn is idempotent (tmp +
                    # rename) — then skip-with-audit. Never a dead run.
                    attempt += 1
                    if attempt > self.io_retries:
                        self._skip(e)
                        return
                    time.sleep(
                        self.io_backoff_s
                        * (2.0 ** (attempt - 1))
                        * self._rng.uniform(0.5, 1.5)
                    )
                except SimulatedCrash as e:
                    # An injected kill of this write: the checkpoint is
                    # simply lost (exactly what a real crash costs) —
                    # audit it and keep the training run alive.
                    self._skip(e)
                    return
                except NonFiniteCheckpointError as e:
                    # The write gate refused a diverged state: skip with
                    # the non-finite audit (its own counter + incident —
                    # a poisoned snapshot is a TRAIN-lane event, not IO
                    # weather) and keep training; the recovery ladder
                    # owns the rollback.
                    self.writes_skipped += 1
                    _audit_nonfinite_skip(Path("<async>"), str(e))
                    return
            registry = get_registry()
            registry.histogram("checkpoint_write_seconds").observe(
                time.perf_counter() - t0
            )
            registry.counter("checkpoint_writes_total").inc()
        except BaseException as e:  # noqa: BLE001 — surfaced on wait()
            self._error = e
        finally:
            get_registry().gauge("checkpoint_queue_depth").set(0.0)

    def _skip(self, error: BaseException) -> None:
        """Audit a degraded (skipped) write: counter + flight record.
        The run stays alive; the next save boundary tries again."""
        from marl_distributedformation_tpu.obs import get_registry, get_tracer

        self.writes_skipped += 1
        get_registry().counter("checkpoint_writes_skipped_total").inc()
        get_tracer().incident(
            "checkpoint_write_skipped",
            error=repr(error)[:300],
            retries=self.io_retries,
            writes_skipped=self.writes_skipped,
        )

    def wait(self) -> None:
        """Join the in-flight write (if any); re-raise its failure."""
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join()
        err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                f"async checkpoint write failed: {err!r}"
            ) from err

    def close(self) -> None:
        """Drain the pipeline; raises if the last write failed."""
        self.wait()

    def close_quietly(self) -> None:
        """Teardown on an already-failing path: join without raising (a
        write error must not mask the exception that is unwinding)."""
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join()
        self._error = None


def save_sweep_state(
    log_dir: str | Path, num_timesteps: int, target: Any
) -> Optional[Path]:
    """Write the full population state of a sweep (train/sweep.py).
    Multi-host: coordinator-only write + durability barrier, same contract
    as :func:`save_checkpoint` (``target`` must be host-addressable on the
    coordinator — SweepTrainer passes the allgathered host population)."""
    import jax

    from marl_distributedformation_tpu.parallel.distributed import (
        is_coordinator,
    )

    path = sweep_state_path(log_dir, num_timesteps)
    on_coordinator = is_coordinator()
    if on_coordinator:
        try:
            _write_atomic(path, target)
        except NonFiniteCheckpointError as e:
            _audit_nonfinite_skip(path, str(e))
            path = None
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"sweep_state_{num_timesteps}")
    return path if on_coordinator else None


def latest_sweep_state(log_dir: str | Path) -> Optional[Path]:
    return _latest(log_dir, _SWEEP_STEP_RE)


def _latest(log_dir: str | Path, step_re: re.Pattern) -> Optional[Path]:
    log_dir = Path(log_dir)
    if not log_dir.is_dir():
        return None
    candidates = [
        p
        for p in log_dir.iterdir()
        if p.suffix == ".msgpack" and step_re.search(p.name)
    ]
    if not candidates:
        return None
    return max(candidates, key=lambda p: int(step_re.search(p.name).group(1)))


def latest_checkpoint(log_dir: str | Path) -> Optional[Path]:
    """Find the checkpoint with the largest step number, exactly like the
    reference's discovery scan (visualize_policy.py:29-32)."""
    return _latest(log_dir, _STEP_RE)


def prune_checkpoints(
    log_dir: str | Path,
    keep_last_n: int,
    protect: Any = (),
) -> List[Path]:
    """Checkpoint retention ring: delete all but the newest
    ``keep_last_n`` DISCOVERABLE ``rl_model_*`` checkpoints in
    ``log_dir`` — a months-long always-learning run's unbounded
    ``logs/{name}/`` growth is itself a robustness bug (the disk it
    fills is the disk the next checkpoint needs).

    Quarantine-aware by construction: only discoverable ``.msgpack``
    files are candidates — ``*.quarantined`` evidence, torn ``.tmp``
    files, ``sweep_state_*`` anchors, and the jsonl audit logs are
    untouched. ``protect`` paths (the recovery ladder's CURRENT
    last-good rollback target) survive no matter their age: pruning the
    only state a rollback could restore would turn a divergence into a
    halt. Best-effort (a prune failure is never worth a dead run);
    returns the paths actually removed and counts them into
    ``checkpoint_pruned_total``."""
    keep_last_n = int(keep_last_n)
    if keep_last_n <= 0:
        return []
    log_dir = Path(log_dir)
    if not log_dir.is_dir():
        return []
    protected = {
        Path(p).resolve() for p in (protect or ()) if p is not None
    }
    candidates = sorted(
        (
            p
            for p in log_dir.iterdir()
            if p.suffix == ".msgpack"
            and not p.name.startswith(".")
            and _STEP_RE.search(p.name)
        ),
        key=lambda p: int(_STEP_RE.search(p.name).group(1)),
        reverse=True,
    )
    pruned: List[Path] = []
    for path in candidates[keep_last_n:]:
        if path.resolve() in protected:
            continue
        try:
            path.unlink()
        except OSError:
            continue
        pruned.append(path)
    if pruned:
        from marl_distributedformation_tpu.obs.metrics import get_registry

        get_registry().counter("checkpoint_pruned_total").inc(len(pruned))
    return pruned


class CheckpointDiscovery:
    """Incremental ``rl_model_*`` discovery for long-running watchers.

    ``latest_checkpoint`` re-lists and re-regexes the WHOLE directory on
    every call — fine for a one-shot CLI, but an always-learning run
    polls its trainer directory for hours while the checkpoint count
    grows without bound, so each poll would degrade O(total
    checkpoints). This class keeps the same discovery contract (same
    filename filter, same step parse, torn ``.tmp`` files invisible —
    pinned by tests/test_pipeline.py) while bounding steady-state polls:

    - Filenames are parsed ONCE: a name→step cache means a re-listing
      only regexes names it has never seen.
    - Idle polls are one ``stat``: the directory's mtime changes
      whenever an entry is added/renamed into it, so an unchanged mtime
      means an unchanged listing. Because mtime granularity is finite,
      the skip is only trusted when the previous listing happened
      comfortably AFTER the recorded mtime (``_MTIME_SLACK_S``) — a
      file landing in the same mtime tick as a listing can therefore
      never be missed, only discovered one listing later.

    ``latest()`` is the non-consuming view (what the fleet coordinator
    polls); ``poll_new()`` is the consuming stream (ascending step
    order, each checkpoint yielded exactly once) the promotion pipeline
    tails. New steps at or below the consumed high-water mark are
    ignored by ``poll_new`` — the same never-go-backward semantics the
    serving registry applies to ``latest_checkpoint``.
    """

    _MTIME_SLACK_S = 2.0

    def __init__(
        self, log_dir: str | Path, start_after_step: int = -1
    ) -> None:
        self.log_dir = Path(log_dir)
        self._known: Dict[str, int] = {}  # filename -> parsed step
        self._high_water = int(start_after_step)
        self._dir_mtime_ns: Optional[int] = None
        self._listing_stable = False  # last listing postdated the mtime

    def _refresh(self) -> None:
        try:
            st = os.stat(self.log_dir)
        except OSError:  # directory not created yet
            self._dir_mtime_ns = None
            self._listing_stable = False
            return
        if (
            self._listing_stable
            and st.st_mtime_ns == self._dir_mtime_ns
        ):
            return  # idle poll: one stat, no listing, no parsing
        now = time.time()
        with os.scandir(self.log_dir) as entries:
            for entry in entries:
                name = entry.name
                if name in self._known or not name.endswith(".msgpack"):
                    continue
                m = _STEP_RE.search(name)
                if m is None:
                    continue
                self._known[name] = int(m.group(1))
        self._dir_mtime_ns = st.st_mtime_ns
        # Trust future mtime-equality skips only if this listing ran
        # strictly after the mtime tick it recorded — otherwise a file
        # created within the same tick could hide behind an "unchanged"
        # mtime forever.
        self._listing_stable = (now - st.st_mtime) > self._MTIME_SLACK_S

    def latest(self) -> Optional[Path]:
        """Newest checkpoint path — ``latest_checkpoint`` semantics,
        incremental cost. Deleted entries (the pipeline's rollback
        RETRACTS demoted checkpoints) are dropped from the cache on
        discovery, so ``latest`` can step back down to an older file."""
        self._refresh()
        while self._known:
            name = max(self._known, key=self._known.__getitem__)
            path = self.log_dir / name
            if path.exists():
                return path
            del self._known[name]
        return None

    def poll_new(self) -> List[Path]:
        """Checkpoints discovered above the consumed high-water mark, in
        ascending step order; advances the mark past everything
        returned."""
        self._refresh()
        fresh = sorted(
            (
                (step, name)
                for name, step in self._known.items()
                if step > self._high_water
            ),
        )
        if fresh:
            self._high_water = fresh[-1][0]
        return [self.log_dir / name for _, name in fresh]


def restore_checkpoint(path: str | Path, template: Any) -> Any:
    """Restore a pytree serialized by ``save_checkpoint`` into the structure
    of ``template`` (same-treedef pytree with correctly-shaped leaves).
    The checksum footer is validated first: damaged bytes are
    quarantined and raise :class:`CorruptCheckpointError` here instead
    of wedging the caller downstream."""
    return serialization.from_state_dict(
        template, msgpack_restore_file(path)
    )


def restore_checkpoint_partial(
    path: str | Path, template: dict
) -> dict:
    """Restore the intersection of a dict checkpoint and a dict template.

    Checkpoints written in different launch modes carry different keys
    (multi-host learner-only checkpoints omit the cross-host-sharded env
    state); this restores every template key present in the file and simply
    omits the rest, so a single-host checkpoint resumes multi-host and vice
    versa. Extra keys in the file are ignored.

    Every restored leaf is validated against the template leaf's shape: a
    checkpoint from a different architecture (other tower widths, another
    policy class) raises a ``ValueError`` naming the offending leaf here,
    at restore time — not a shape crash later inside a compiled train step
    or serving act function.
    """
    raw = msgpack_restore_file(path)
    assert isinstance(raw, dict), f"checkpoint at {path} is not a dict"
    return restore_state_dict_partial(raw, template, origin=str(path))


def restore_latest_partial(
    log_dir: str | Path, template: dict
) -> Optional[tuple]:
    """Resume from the newest VALID checkpoint: walk the discovery
    order newest-first, quarantining corrupt/truncated files as they
    are found, until one restores — a crashed writer or a bad sector
    costs one checkpoint of progress, never a wedged resume. Returns
    ``(path, restored)`` or None when no restorable checkpoint exists.
    Architecture mismatches still raise (that is a config error, not
    damage)."""
    while True:
        path = latest_checkpoint(log_dir)
        if path is None:
            return None
        try:
            return path, restore_checkpoint_partial(path, template)
        except CorruptCheckpointError:
            # Reader already quarantined the file (renamed aside), so
            # the next latest_checkpoint scan steps down one. If the
            # rename FAILED (read-only remount, permissions), the same
            # corrupt path stays discoverable forever — surface the
            # corruption instead of spinning (and flooding the flight
            # recorder with one incident per iteration).
            if path.exists():
                raise


def restore_state_dict_partial(
    raw: dict, template: dict, origin: str = "<state dict>"
) -> dict:
    """`restore_checkpoint_partial` over an already-parsed state dict
    (the serving registry reads the file once for its header check and
    restores from the same parse). Same intersection + leaf-shape
    validation contract; ``origin`` names the source in errors."""
    restored = {}
    for key, tmpl in template.items():
        if key not in raw:
            continue
        try:
            value = serialization.from_state_dict(tmpl, raw[key])
        except Exception as e:  # noqa: BLE001 — any flax restore failure
            # flax raises on structural mismatch (missing/renamed nested
            # keys as ValueError/KeyError, array-where-dict as
            # AttributeError/TypeError — all of them a different
            # architecture); add which file and key.
            raise ValueError(
                f"checkpoint {origin}: key {key!r} does not match the "
                f"restore template (architecture mismatch?): {e!r}"
            ) from e
        _check_leaf_shapes(tmpl, value, origin, key)
        restored[key] = value
    return restored


def _check_leaf_shapes(tmpl: Any, restored: Any, origin: str, key: str) -> None:
    """Leaf-by-leaf shape (and, for array leaves, dtype) comparison of a
    restored subtree against its template. ``from_state_dict`` copies
    leaf values verbatim, so a same-structure checkpoint with different
    layer widths — or same shapes at a drifted dtype — restores silently
    and only explodes later inside jit (a dtype drift is worse than a
    crash: it is a retrace, which a serving RetraceGuard turns into a
    permanent failure). Catch both here with the leaf path in hand.
    Dtype is compared only when BOTH leaves are arrays: scalar template
    leaves like ``num_timesteps: 0`` legitimately restore as whatever
    integer width the writer used."""
    import jax
    import numpy as np

    t_leaves, t_def = jax.tree_util.tree_flatten_with_path(tmpl)
    r_leaves, r_def = jax.tree_util.tree_flatten_with_path(restored)
    if t_def != r_def:
        # from_state_dict can hand back a DEEPER tree than the template
        # (a dict where an array leaf belongs restores verbatim) — a
        # plain leaf zip would silently pair across the drift.
        raise ValueError(
            f"checkpoint {origin}: key {key!r} tree structure does not "
            f"match the restore template — architecture mismatch "
            f"(template {t_def}, checkpoint {r_def})"
        )
    for (t_path, t_leaf), (_, r_leaf) in zip(t_leaves, r_leaves):
        t_shape, r_shape = np.shape(t_leaf), np.shape(r_leaf)
        problem = None
        if t_shape != r_shape:
            problem = f"shape {r_shape}, but the template expects {t_shape}"
        else:
            t_dtype = getattr(t_leaf, "dtype", None)
            r_dtype = getattr(r_leaf, "dtype", None)
            if (
                t_dtype is not None
                and r_dtype is not None
                and t_dtype != r_dtype
            ):
                problem = (
                    f"dtype {r_dtype}, but the template expects {t_dtype}"
                )
        if problem:
            leaf_name = jax.tree_util.keystr(t_path)
            raise ValueError(
                f"checkpoint {origin}: key {key!r} leaf {leaf_name} has "
                f"{problem} — architecture mismatch (refusing to restore "
                "an incompatible tree)"
            )


def broadcast_restore(log_dir: str | Path, template: dict) -> Optional[dict]:
    """Multi-host resume: the coordinator reads its latest checkpoint and
    every host receives the identical restored state.

    Checkpoints exist on the coordinator's disk only, so both the
    found/not-found decision and the state are broadcast — otherwise hosts
    would disagree on params/counters and the SPMD loop would deadlock on
    mismatched collective counts. ``template`` must be array/scalar leaves
    only (no strings — they can't ride the broadcast). Returns None when no
    checkpoint exists; all template keys must be present in the file.
    """
    import numpy as np
    from jax.experimental import multihost_utils

    from marl_distributedformation_tpu.parallel.distributed import (
        is_coordinator,
    )

    # ALL fallible coordinator work happens before the first broadcast:
    # if the coordinator raised mid-protocol, the other hosts would block
    # forever inside broadcast_one_to_all (a silent cluster hang). On
    # failure the coordinator broadcasts found=0 first — peers proceed with
    # a fresh start — and then re-raises so the launcher tears the job down
    # with a real error.
    restored, found, err = template, 0, None
    if is_coordinator():
        try:
            path = latest_checkpoint(log_dir)
            if path is not None:
                restored = restore_checkpoint_partial(path, template)
                missing = set(template) - set(restored)
                if missing:
                    raise ValueError(
                        f"checkpoint {path} is missing learner state "
                        f"{missing}"
                    )
                found = 1
        except Exception as e:  # noqa: BLE001 — converted to fail-fast
            restored, found, err = template, 0, e
    found = int(multihost_utils.broadcast_one_to_all(np.int32(found)))
    if err is not None:
        raise err
    if not found:
        return None
    return multihost_utils.broadcast_one_to_all(restored)


def checkpoint_step(path: str | Path) -> int:
    m = _STEP_RE.search(Path(path).name)
    if not m:
        raise ValueError(f"not a checkpoint path: {path}")
    return int(m.group(1))
