"""Profiling hooks (the reference has none — SURVEY.md §5).

Thin wrappers over ``jax.profiler`` plus a steps/sec meter, so any training
run can produce a TensorBoard-loadable TPU trace and throughput numbers.
Wired into training via ``TrainConfig.profile`` / the ``profile=true`` CLI
flag (train/trainer.py): the trainer captures a trace of a few post-warmup
iterations into ``{log_dir}/profile/`` and the jitted iteration is
``jax.named_scope``-annotated (rollout / policy / env_step / gae /
ppo_update) so the trace viewer attributes time to pipeline stages.
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Iterator, Optional

import jax

# Runtime tracing guards (the dynamic half of graftlint — see
# analysis/guards.py and docs/static_analysis.md): re-exported here so
# training code and notebooks reach them through the same module that
# owns the other observability hooks. Opt-in from TrainConfig via
# guard_retraces / guard_transfers / guard_nans.
from marl_distributedformation_tpu.analysis.guards import (  # noqa: F401
    RetraceError,
    RetraceGuard,
    nan_guard,
    no_host_transfers,
)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace into ``log_dir`` (no-op if None)."""
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Throughput:
    """Steps/sec meter over a rolling window of recent ticks.

    The first tick only starts the clock (that iteration's time includes
    compilation); after that the rate reflects the last ``window`` ticks, so
    quoted numbers converge to steady-state instead of blending early
    dispatch-bound iterations forever (round-1 VERDICT weak #6).
    """

    def __init__(self, window: int = 20) -> None:
        # (timestamp, cumulative_steps) ring; rate = slope over the ring.
        self._ticks: collections.deque = collections.deque(maxlen=window + 1)
        self._cum = 0

    def tick(self, steps: int = 1) -> None:
        if not self._ticks:  # first call: clock start only (compile)
            self._ticks.append((time.perf_counter(), 0))
            return
        self._cum += steps
        self._ticks.append((time.perf_counter(), self._cum))

    def rate(self) -> float:
        if len(self._ticks) < 2:
            return 0.0
        (t0, s0), (t1, s1) = self._ticks[0], self._ticks[-1]
        if t1 <= t0:
            return 0.0
        return (s1 - s0) / (t1 - t0)
