"""Profiling hooks (the reference has none — SURVEY.md §5).

Thin wrappers over ``jax.profiler`` plus a steps/sec meter, so any training
run can produce a TensorBoard-loadable TPU trace and throughput numbers.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace into ``log_dir`` (no-op if None)."""
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Throughput:
    """Steps/sec meter with warmup exclusion (first call is compile)."""

    def __init__(self) -> None:
        self._t0: Optional[float] = None
        self._steps = 0

    def tick(self, steps: int = 1) -> None:
        if self._t0 is None:  # exclude compile/warmup iteration
            self._t0 = time.perf_counter()
            return
        self._steps += steps

    def rate(self) -> float:
        if self._t0 is None or self._steps == 0:
            return 0.0
        return self._steps / (time.perf_counter() - self._t0)
