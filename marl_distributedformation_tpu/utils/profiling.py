"""Profiling hooks (the reference has none — SURVEY.md §5).

Thin wrappers over ``jax.profiler`` plus a steps/sec meter, so any training
run can produce a TensorBoard-loadable TPU trace and throughput numbers.
Wired into training via ``TrainConfig.profile`` / the ``profile=true`` CLI
flag (train/trainer.py): the trainer captures a trace of a few post-warmup
iterations into ``{log_dir}/profile/`` and the jitted iteration is
``jax.named_scope``-annotated (rollout / policy / env_step / gae /
ppo_update) so the trace viewer attributes time to pipeline stages.
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Iterator, Optional

import jax

# Runtime tracing guards (the dynamic half of graftlint — see
# analysis/guards.py and docs/static_analysis.md): re-exported here so
# training code and notebooks reach them through the same module that
# owns the other observability hooks. Opt-in from TrainConfig via
# guard_retraces / guard_transfers / guard_nans.
from marl_distributedformation_tpu.analysis.guards import (  # noqa: F401
    LedgerDispatch,
    RetraceError,
    RetraceGuard,
    device_memory_bytes,
    ledgered_jit,
    nan_guard,
    no_host_transfers,
    register_aot_program,
    sample_device_watermark,
)


class TraceWindow:
    """Dispatch-grained ``jax.profiler`` capture window for training
    loops (the ``profile=true`` implementation shared by the host-loop,
    fused-scan, and population-sweep drivers).

    The unit is one *dispatch* — a single iteration in the host loop, a
    whole fused chunk in Anakin mode — so ``profile=true`` composes with
    ``fused_chunk``: tracing ``count`` dispatches captures ``count``
    chunks (K iterations each) instead of fail-fasting. The first
    ``skip`` dispatches are excluded (they are compile-bound and would
    dominate the trace), and the window closes after syncing the last
    traced dispatch's outputs so the trace contains the full device
    execution, not just the async enqueue.

    Start/stop never touch the jit cache — a traced run compiles exactly
    as often as an untraced one (pinned by the profiler-under-fused
    smoke tests).

    Every completed (or aborted) window appends one JSON line to
    ``{trace_dir}/capture_ledger.jsonl`` naming what actually ran:
    the programs dispatched during the window (from the ProgramLedger's
    per-program dispatch counters), the chunk count, and the trace
    directory — so a profile artifact found weeks later is attributable
    without replaying the run.
    """

    AUDIT_NAME = "capture_ledger.jsonl"

    def __init__(
        self,
        log_dir: Optional[str],
        enabled: bool,
        count: int = 3,
        skip: int = 1,
    ) -> None:
        import os

        self.trace_dir = (
            os.path.join(log_dir, "profile") if log_dir else None
        )
        self.enabled = bool(enabled) and self.trace_dir is not None
        self.count = max(1, int(count))
        self.skip = max(0, int(skip))
        self._dispatches = 0
        self._traced = 0
        self.active = False
        self.captured = False
        self._window_baseline: Optional[dict] = None

    @staticmethod
    def _program_dispatches() -> dict:
        """``{dispatch_key: dispatches_total}`` from the ProgramLedger
        (empty when the ledger is disabled)."""
        from marl_distributedformation_tpu.obs.ledger import get_ledger

        suffix = "_dispatches_total"
        return {
            key[len("program_"):-len(suffix)]: value
            for key, value in get_ledger().snapshot().items()
            if key.startswith("program_") and key.endswith(suffix)
        }

    def _audit_line(self, completed: bool) -> None:
        """One durable line per capture window — never raises, never
        blocks the training loop on anything but one small append."""
        import json
        import os

        baseline, self._window_baseline = self._window_baseline, None
        try:
            now = self._program_dispatches()
            programs = {
                key: int(count - (baseline or {}).get(key, 0))
                for key, count in now.items()
                if count - (baseline or {}).get(key, 0) > 0
            }
            line = {
                "event": "profile_capture",
                "time": time.time(),
                "trace_dir": self.trace_dir,
                "completed": completed,
                "dispatches_traced": self._traced,
                "dispatches_skipped": self.skip,
                "programs": programs,
            }
            os.makedirs(self.trace_dir, exist_ok=True)
            with open(
                os.path.join(self.trace_dir, self.AUDIT_NAME), "a"
            ) as f:
                f.write(json.dumps(line) + "\n")
        except Exception:  # noqa: BLE001 — attribution is best-effort
            pass

    def before_dispatch(self) -> None:
        """Open the window once the warmup dispatches have passed."""
        if (
            self.enabled
            and not self.captured
            and not self.active
            and self._dispatches >= self.skip
        ):
            self._window_baseline = self._program_dispatches()
            jax.profiler.start_trace(self.trace_dir)
            self.active = True
            print(f"[profile] tracing -> {self.trace_dir}")

    def after_dispatch(self, sync_tree: Optional[object] = None) -> None:
        """Count the dispatch; once ``count`` traced dispatches are in,
        block on ``sync_tree`` (the dispatch's outputs) and stop."""
        self._dispatches += 1
        if not self.active:
            return
        self._traced += 1
        if self._traced >= self.count:
            if sync_tree is not None:
                jax.block_until_ready(sync_tree)
            jax.profiler.stop_trace()
            self.active = False
            self.captured = True
            self._audit_line(completed=True)

    def close(self) -> None:
        """Teardown guard for error paths: stop an open trace so the
        profiler session never leaks across runs."""
        if self.active:
            jax.profiler.stop_trace()
            self.active = False
            self._audit_line(completed=False)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace into ``log_dir`` (no-op if None)."""
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Throughput:
    """Steps/sec meter over a rolling window of recent ticks.

    The first tick only starts the clock (that iteration's time includes
    compilation); after that the rate reflects the last ``window`` ticks, so
    quoted numbers converge to steady-state instead of blending early
    dispatch-bound iterations forever (round-1 VERDICT weak #6).
    """

    def __init__(self, window: int = 20) -> None:
        # (timestamp, cumulative_steps) ring; rate = slope over the ring.
        self._ticks: collections.deque = collections.deque(maxlen=window + 1)
        self._cum = 0

    def tick(self, steps: int = 1) -> None:
        if not self._ticks:  # first call: clock start only (compile)
            self._ticks.append((time.perf_counter(), 0))
            return
        self._cum += steps
        self._ticks.append((time.perf_counter(), self._cum))

    def rate(self) -> float:
        if len(self._ticks) < 2:
            return 0.0
        (t0, s0), (t1, s1) = self._ticks[0], self._ticks[-1]
        if t1 <= t0:
            return 0.0
        return (s1 - s0) / (t1 - t0)
