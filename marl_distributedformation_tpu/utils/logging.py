"""Metrics logging: on-device accumulation, per-rollout host emission.

Replaces the reference's wandb streaming (SURVEY.md §5): the reference calls
``wandb.log`` once per formation per step plus 7 times per step from the
reward/metrics path (Q7 — thousands of network-bound calls per vec-step).
Here metrics are reduced inside the jitted train step and emitted once per
rollout to a JSONL file, stdout, and optionally wandb and/or tensorboard
(if installed and enabled; SB3 also writes ``tensorboard_log`` scalars for
the reference, vectorized_env.py:129 — ``use_tensorboard=True`` restores
that capability via ``torch.utils.tensorboard``, no host-callback cost
since emission stays per-rollout). Metric names preserve the reference's
observability contract
(``close_to_goal_reward``, ``reward_dist``, ``reward_right_neighbor``,
``reward_left_neighbor``, ``avg_dist_to_goal``, ``ave_dist_to_neighbor``,
``std_dist_to_neighbor``, ``reward`` — simulate.py:188-254,
vectorized_env.py:80-81).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path
from typing import Any, Dict


class MetricsLogger:
    def __init__(
        self,
        log_dir: str | Path,
        run_name: str = "run",
        use_wandb: bool = False,
        wandb_project: str = "formation-rl",
        stdout_every: int = 10,
        use_tensorboard: bool = False,
    ) -> None:
        from marl_distributedformation_tpu.parallel.distributed import (
            is_coordinator,
        )

        # Multi-host: metrics in the jitted step are already globally
        # reduced, so only the coordinator emits; other hosts no-op.
        self._active = is_coordinator()
        self.log_dir = Path(log_dir)
        self.jsonl_path = self.log_dir / "metrics.jsonl"
        self._file = None
        if self._active:
            self.log_dir.mkdir(parents=True, exist_ok=True)
            self._file = open(self.jsonl_path, "a", buffering=1)
        self.stdout_every = stdout_every
        self._emit_count = 0
        self._start = time.time()

        self._wandb = None
        use_wandb = use_wandb and self._active
        if use_wandb:
            try:
                import wandb

                # Run naming matches the reference: "{name}-{timestamp}"
                # (vectorized_env.py:117-118).
                stamp = time.strftime("%Y-%m-%d-%H-%M")
                self._wandb = wandb.init(
                    project=wandb_project, name=f"{run_name}-{stamp}"
                )
            except Exception as e:  # pragma: no cover - wandb optional
                print(f"[metrics] wandb unavailable ({e}); using JSONL only")

        self._tb = None
        if use_tensorboard and self._active:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(
                    log_dir=str(self.log_dir / "tensorboard")
                )
            except Exception as e:  # pragma: no cover - tb optional
                print(
                    f"[metrics] tensorboard unavailable ({e}); "
                    "using JSONL only"
                )

    def log(self, metrics: Dict[str, Any], step: int) -> None:
        """Emit one metrics record at ``step`` (agent-transitions)."""
        if not self._active:
            return
        record = {"step": int(step), "time": time.time() - self._start}
        for k, v in metrics.items():
            record[k] = float(v)
        self._file.write(json.dumps(record) + "\n")
        if self._wandb is not None:
            self._wandb.log(record, step=int(step))
        if self._tb is not None:
            for k, v in record.items():
                if k != "step":
                    self._tb.add_scalar(k, v, int(step))
        self._emit_count += 1
        if self.stdout_every and self._emit_count % self.stdout_every == 1:
            brief = {
                k: round(record[k], 4)
                for k in ("reward", "avg_dist_to_goal", "loss", "approx_kl")
                if k in record
            }
            print(f"[metrics] step={record['step']} {brief}", file=sys.stderr)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
        if self._wandb is not None:
            self._wandb.finish()
        if self._tb is not None:
            self._tb.close()
