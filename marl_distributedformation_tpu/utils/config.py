"""Hydra-compatible configuration loading.

The reference wires its CLI through ``@hydra.main(config_path="cfg",
config_name="config")`` with ``key=value`` overrides (vectorized_env.py:112,
README.md:18). This module preserves that exact CLI contract — ``python
train.py name=x num_formation=16`` — with a small, dependency-free YAML +
override parser (hydra itself is not installable in the TPU image;
SURVEY.md §2.2). Hydra features beyond flat ``key=value``/dotted overrides
(config groups, ``${...}`` interpolation, multirun) are intentionally out of
scope: the reference uses none of them.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional

import yaml

# Dot-less scientific notation that YAML 1.1 fails to parse as a float.
_SCI_NOTATION_RE = re.compile(r"^[+-]?\d+(\.\d*)?[eE][+-]?\d+$")


class Config(dict):
    """Dict with attribute access, mirroring omegaconf's DictConfig usage
    in the reference (``cfg.num_formation`` etc.)."""

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError as e:  # pragma: no cover
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value


def _parse_value(raw: str) -> Any:
    """Parse an override value with YAML semantics (hydra behavior):
    ``true``/``false`` -> bool, numbers -> int/float, ``null`` -> None.

    YAML 1.1 leaves dot-less scientific notation (``3e-4``) as a string;
    hydra parses it as a float, so coerce exactly that shape — and nothing
    else, so string-typed values like ``name=2024a`` survive untouched."""
    value = yaml.safe_load(raw)
    if isinstance(value, str) and _SCI_NOTATION_RE.match(value):
        return float(value)
    return value


def apply_overrides(cfg: Dict[str, Any], overrides: Iterable[str]) -> None:
    """Apply ``key=value`` (dotted keys allowed) overrides in place.

    Unknown top-level keys are accepted, as in hydra's default struct-less
    mode for this config (the reference's cfg is flat and unvalidated).
    """
    for item in overrides:
        if "=" not in item:
            raise ValueError(
                f"override {item!r} is not of the form key=value"
            )
        key, raw = item.split("=", 1)
        target = cfg
        parts = key.split(".")
        for part in parts[:-1]:
            # Replace null/scalar intermediates so `mesh.dp=4` works when the
            # config ships `mesh: null`.
            if not isinstance(target.get(part), dict):
                target[part] = Config()
            target = target[part]
        target[parts[-1]] = _parse_value(raw)


# Named hyperparameter presets (``preset=tpu`` on any entry point).
# Precedence: YAML defaults < preset < explicit CLI overrides — so
# ``python train.py preset=tpu batch_size=4096`` keeps the user's batch size.
#
# "tpu": the TPU-shaped training configuration. The parity defaults inherit
# SB3's batch_size=64, which turns each update into n_epochs x (rollout/64)
# *sequential* tiny SGD steps — at M=4096 that is 32,000 serial launches of
# MXU-starving (64, obs_dim) matmuls, 98% of iteration wall-clock
# (docs/profiling.md). A large batch_size keeps the same epochs/passes over
# the data with far fewer, far larger steps — the shape the MXU wants.
# 16384 is the measured sweet spot from the on-chip sweep
# (docs/acceptance/tpu_tuning_r4.txt): +7% throughput over 8192 AND a
# better held-out eval return (5271 vs 5078 in the same harness); 32768 is
# marginally faster but gives back eval quality, and the full-buffer point
# (one minibatch per epoch) fails the quality guard outright.
PRESETS: Dict[str, Dict[str, Any]] = {
    "tpu": {"batch_size": 16384},
}


def load_config(
    overrides: Optional[List[str]] = None,
    config_path: str = "cfg/config.yaml",
) -> Config:
    """Load the YAML config and apply presets + CLI overrides.

    ``config_path`` is resolved relative to the repo root (this file's
    grandparent), so entry points work from any cwd — the equivalent of the
    reference's ``hydra.utils.get_original_cwd()`` dance
    (vectorized_env.py:121)."""
    path = Path(config_path)
    if not path.is_absolute() and not path.exists():
        path = repo_root() / config_path
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    cfg = _to_config(data)
    overrides = list(overrides or [])
    preset = next(
        (
            _parse_value(o.split("=", 1)[1])
            for o in reversed(overrides)
            if "=" in o and o.split("=", 1)[0] == "preset"
        ),
        data.get("preset"),
    )  # a bare "preset" token falls through to apply_overrides' error
    if preset:
        if preset not in PRESETS:
            raise ValueError(
                f"unknown preset {preset!r}; available: {sorted(PRESETS)}"
            )
        cfg.update(_to_config(PRESETS[preset]))
    apply_overrides(cfg, overrides)
    return cfg


def _to_config(data: Any) -> Any:
    if isinstance(data, dict):
        return Config({k: _to_config(v) for k, v in data.items()})
    return data


def setup_platform(platform: Optional[str]) -> None:
    """Force a JAX backend before first device use (the ``platform=cpu``
    CLI knob shared by every entry point). ``JAX_PLATFORMS`` env vars are
    too late under this image's sitecustomize (it imports jax at interpreter
    start), so this calls ``jax.config.update`` instead. No-op on falsy."""
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def repo_root() -> Path:
    """Root of this repository (where ``cfg/`` and ``logs/`` live)."""
    return Path(__file__).resolve().parent.parent.parent


def scenario_schedule_from_config(cfg: Config):
    """Build the scenario-training schedule from the flat config
    (``scenarios`` + ``scenario_severity`` keys, cfg/config.yaml) — None
    when scenario training is off. Unknown scenario names fail fast here,
    at config time, naming the registry entries."""
    raw = cfg.get("scenarios")
    if not raw:
        return None
    from marl_distributedformation_tpu.scenarios import schedule_from_cfg

    return schedule_from_cfg(
        raw, default_severity=float(cfg.get("scenario_severity") or 0.0)
    )


def _env_spec_or_exit(name: str):
    """Resolve a registered env by name, converting the registry's
    ValueError (did-you-mean + listing) into the entry-point SystemExit."""
    from marl_distributedformation_tpu.envs import get_env

    try:
        return get_env(str(name))
    except ValueError as e:
        raise SystemExit(str(e)) from e


def validate_override_keys(
    overrides: Iterable[str],
    extra_keys: Iterable[str] = (),
    config_path: str = "cfg/config.yaml",
) -> None:
    """Fail fast on mistyped CLI override keys (read-only entry points).

    ``train.py`` keeps hydra's struct-less tolerance (experimental knobs
    ride along in the config snapshot), but evaluation entry points have
    no snapshot to expose the typo — an unknown key silently evaluates
    the default (e.g. the clean env), which is exactly the failure mode
    this guards. Valid keys = the YAML defaults + ``extra_keys``; dotted
    overrides validate their top-level segment."""
    overrides = list(overrides)
    path = Path(config_path)
    if not path.is_absolute() and not path.exists():
        path = repo_root() / config_path
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    known = set(data)
    # Every field of the SELECTED env's params class is honored by
    # env_params_from_config even when the YAML defaults omit it (e.g.
    # max_steps, pursuer_speed) — all are valid overrides. Peek the env=
    # override the same way load_config peeks preset=, so a mistyped env
    # name fails here with the registry's did-you-mean, and env-specific
    # knobs (PursuitParams.capture_radius, ...) validate precisely.
    env_name = next(
        (
            _parse_value(o.split("=", 1)[1])
            for o in reversed(overrides)
            if "=" in o and o.split("=", 1)[0] == "env"
        ),
        data.get("env", "formation"),
    )
    spec = _env_spec_or_exit(env_name)
    known |= {f.name for f in dataclasses.fields(spec.params_cls)}
    known |= {"env"}
    known |= set(extra_keys)
    for item in overrides:
        if "=" not in item:
            continue  # apply_overrides raises its own error for these
        key = item.split("=", 1)[0].split(".")[0]
        if key not in known:
            import difflib

            close = difflib.get_close_matches(key, known, n=1)
            hint = f" (did you mean {close[0]!r}?)" if close else ""
            raise SystemExit(
                f"unknown config key {key!r}{hint}; valid keys: "
                f"{', '.join(sorted(known))}"
            )


def env_params_from_config(cfg: Config):
    """Build env params from the flat config, forwarding every knob —
    including ``share_reward_ratio``, which the reference silently drops
    (SURVEY.md Q6).

    The ``env`` key (cfg/config.yaml) selects which REGISTERED environment's
    params class to build (``envs.get_env`` — unknown names exit with the
    registry's did-you-mean), so ``env=pursuit_evasion`` routes every env
    consumer (train.py, evaluate.py, the robustness matrix) through
    ``envs.spec_for_params`` dispatch with no further plumbing. Default is
    the formation env, whose params class is the legacy ``EnvParams``."""
    spec = _env_spec_or_exit(cfg.get("env", "formation"))
    fields = {f.name for f in dataclasses.fields(spec.params_cls)}
    kwargs = {
        "num_agents": cfg.num_agents_per_formation,
        "share_reward_ratio": cfg.share_reward_ratio,
        "goal_in_obs": cfg.goal_in_obs,
    }
    for key in fields:
        if key in cfg and key not in ("num_agents",):
            kwargs[key] = cfg[key]
    return spec.params_cls(**kwargs)
