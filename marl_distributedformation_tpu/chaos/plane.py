"""FaultPlane: deterministic fault injection at the host seams.

Five PRs of failure machinery (circuit break, failover, wedged-barrier
abort, rollback, torn-write invisibility) each earned ONE hand-written
test. This module makes arbitrary fault sequences cheap: the code that
owns a host seam declares a named **injection point**
(:func:`fault_point`), and a seeded :class:`FaultSchedule` arms faults
at those points — crash before/after a checkpoint rename, a wedged gate
eval, ENOSPC under the async writer, a bit-flipped checkpoint byte — so
a chaos campaign replays bit-identically from its seed instead of
depending on thread timing.

Design constraints, in the MetricsRegistry/Tracer tradition:

1. **Disabled is free.** The process-global plane ships disabled;
   :func:`fault_point` is one global load + one attribute read + return.
   Injection points therefore stay wired into production seams
   unconditionally, exactly like tracer spans and registry counters.
2. **Never in the compiled path.** Injection points live at host seams
   only — graftlint rule 19 (``fault-point-in-traced-scope``) statically
   rejects a ``fault_point``/``plane.hit`` call reachable inside a
   jit/scan/vmap traced scope, so budget-1 compile receipts hold with
   chaos armed.
3. **Deterministic.** A fault fires at the N-th *hit* of its point
   (per-point hit counters are deterministic on the thread that owns
   the seam), and :meth:`FaultSchedule.from_seed` is a pure function of
   its seed — same seed, same armed schedule, byte for byte.

This module never imports jax.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: Everything a schedule may arm. ``crash`` raises
#: :class:`SimulatedCrash` (a BaseException — ordinary ``except
#: Exception`` containment must NOT swallow a kill); ``raise`` raises
#: :class:`InjectedFault`; ``enospc`` raises ``OSError(ENOSPC)``;
#: ``delay``/``wedge`` sleep (a wedge is a delay sized past the
#: watchdog/commit timeout it exists to trip); ``truncate``/``bitflip``
#: corrupt the file the point passes as ``path``.
FAULT_KINDS = (
    "crash", "raise", "enospc", "delay", "wedge", "truncate", "bitflip",
)

#: Kinds that need the injection point to pass a ``path``.
FILE_KINDS = frozenset({"truncate", "bitflip"})

#: Kinds that interrupt service (the storm measures MTTR from these).
DISRUPTIVE_KINDS = frozenset({"crash", "wedge"})

#: The injection-point catalogue: every host seam that declares a
#: :func:`fault_point`, with the fault kinds that make sense there
#: (docs/chaos.md walks each one). ``FaultSchedule.from_seed`` draws
#: from this table; arming a kind a point cannot express (a bitflip
#: with no file in hand) is a schedule-construction error, not a silent
#: no-op at fire time.
INJECTION_POINTS: Dict[str, Tuple[str, ...]] = {
    # utils/checkpoint._write_atomic — the torn-write seam. Failure
    # modes here are IO-shaped by construction: ENOSPC (retried, then
    # skip-with-audit), crash (the write is lost), corruption. A
    # generic ``raise`` would be a PROGRAM error, which the writer
    # rightly surfaces instead of degrading — so it is not armable.
    "checkpoint.write": ("enospc", "delay"),
    "checkpoint.pre_rename": ("crash", "delay"),
    "checkpoint.post_rename": ("crash", "truncate", "bitflip"),
    # utils/checkpoint.AsyncCheckpointWriter.submit_write (the TRAINING
    # thread: only a stall makes sense — an exception here would kill
    # the training loop, which is the writer's surfacing contract).
    "ckpt_writer.submit": ("delay",),
    # train/trainer.py dispatch boundary — the train lane's divergence
    # seams (train/recovery.py, docs/recovery.md). A 'raise' armed here
    # is CAUGHT by the seam and interpreted as state corruption: the
    # deterministic stand-in for organic divergence the in-program
    # health word + recovery ladder must absorb.
    #   carry_poison: NaN bomb into the live params (loss goes NaN,
    #     every later iteration is flagged until the ladder rolls back)
    "train.carry_poison": ("raise", "delay"),
    #   grad_bomb: a FINITE 1e18 scale on the params — loss/gradients
    #   explode without NaN, exercising the bounded-grad-norm and
    #   param-drift checks (and the finite-but-poisoned-checkpoint
    #   quarantine walk) rather than the finiteness ones.
    "train.grad_bomb": ("raise",),
    #   snapshot: checkpoint-time state corruption — poisons the
    #   snapshot COPY handed to the writer (never the live carry); the
    #   non-finite write gate (utils/checkpoint.py) must keep it
    #   invisible to discovery.
    "train.snapshot": ("raise", "delay"),
    # train/sebulba/queues.py — the transfer seams between the actor
    # and learner slices (docs/sebulba.md). Each seam CATCHES an armed
    # 'raise' and interprets it as that seam's characteristic transport
    # failure; the lane invariants (chaos/invariants.py) then pin that
    # the plumbing degrades instead of corrupting.
    #   enqueue: DROP — the trajectory batch vanishes in transfer (its
    #     seq is spent: downstream sees a gap, never a duplicate).
    "sebulba.enqueue": ("raise", "delay"),
    #   dequeue: DUPLICATE — the delivered item is re-queued at the
    #     head (a retrying-consumer bug's shape); the queue's seq guard
    #     must absorb the redelivery (no trajectory consumed twice).
    "sebulba.dequeue": ("raise", "delay"),
    #   param_publish: STALE PARAMS — the learner's publish is dropped,
    #     actors keep acting on the previous version; the learner's
    #     staleness gate bounds how old a consumed batch may be.
    "sebulba.param_publish": ("raise", "delay"),
    # pipeline/stream.CheckpointStream.poll.
    "stream.poll": ("raise", "delay"),
    # pipeline/gate.PromotionGate eval body (runs on the gate's thread,
    # so a wedge here exercises the gate_timeout_s deadline).
    "gate.eval": ("wedge", "delay", "raise"),
    # pipeline/supervisor run-loop body (the watchdog's lane).
    "pipeline.poll": ("crash", "wedge", "delay", "raise"),
    # serving/fleet/reload barrier acquisition + registry swap.
    "fleet.barrier": ("raise", "delay"),
    "registry.swap": ("raise", "delay"),
    # serving/scheduler worker loop (a crash here is a worker death the
    # router must circuit-break and fail over).
    "scheduler.dispatch": ("crash", "delay"),
    # serving/fleet/frontend HTTP handler.
    "frontend.handler": ("raise", "delay"),
    # serving/mesh — the cross-host tier's control-plane seams.
    # Coordinator side: the barrier RPC legs (prepare/commit round
    # trips) and the heartbeat handler; a delay here stretches a
    # global commit, a raise aborts the round (every host restored).
    "mesh.rpc": ("raise", "delay"),
    "mesh.heartbeat": ("raise", "delay"),
    # Host-agent side: the staged two-phase handlers. A wedge on
    # mesh.prepare is the canonical wedged-host case — the
    # coordinator's prepare timeout must abort the WHOLE round and
    # every host must resume on the old step.
    "mesh.prepare": ("wedge", "raise", "delay"),
    "mesh.commit": ("raise", "delay"),
    # serving/elastic — the capacity controller's re-split seams. A
    # raise at prewarm aborts the round before anything routes (old
    # split keeps serving, compiles already paid are receipted and
    # reusable); at commit it fires INSIDE the closed barrier before
    # the membership swap (the swap is one list assignment — nothing
    # to untear, gates reopen on the old split); at retire it fires in
    # the drain worker AFTER the new split routes (the retired replica
    # is stopped undrained and its queued requests fail over).
    "elastic.prewarm": ("raise", "delay"),
    "elastic.commit": ("raise", "delay"),
    "elastic.retire": ("raise", "delay"),
}


class InjectedFault(RuntimeError):
    """A deliberately injected failure (kind ``raise``)."""


class SimulatedCrash(BaseException):
    """An injected kill of the current component.

    Deliberately a ``BaseException``: the blanket ``except Exception``
    containment at every seam must treat this like a real ``kill -9`` —
    the component dies and its supervisor (watchdog, router circuit
    breaker, writer skip-with-audit) owns the recovery, not the local
    try/except.
    """


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One armed fault: fire ``kind`` on the ``at_hit``-th hit
    (1-based) of injection point ``point``."""

    point: str
    kind: str
    at_hit: int
    seconds: float = 0.0  # delay/wedge duration

    def record(self) -> dict:
        """Deterministic JSON shape (key order fixed by construction)."""
        return {
            "point": self.point,
            "kind": self.kind,
            "at_hit": self.at_hit,
            "seconds": round(self.seconds, 4),
        }


class FaultSchedule:
    """An ordered, deterministic set of :class:`FaultSpec`.

    ``from_seed`` is a pure function of ``(seed, faults, points, kinds,
    ...)`` — the reason a failing campaign replays bit-identically. At
    most one fault per ``(point, at_hit)`` cell, so firing order within
    a point is total.
    """

    def __init__(self, specs: List[FaultSpec], seed: Optional[int] = None):
        seen: set = set()
        for spec in specs:
            if spec.kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {spec.kind!r}")
            allowed = INJECTION_POINTS.get(spec.point)
            if allowed is not None and spec.kind not in allowed:
                raise ValueError(
                    f"point {spec.point!r} cannot express kind "
                    f"{spec.kind!r} (allowed: {allowed})"
                )
            cell = (spec.point, spec.at_hit)
            if cell in seen:
                raise ValueError(f"duplicate fault cell {cell}")
            seen.add(cell)
        self.specs = list(specs)
        self.seed = seed

    def __len__(self) -> int:
        return len(self.specs)

    def record(self) -> List[dict]:
        """Schedule as JSON-ready dicts, sorted ``(point, at_hit)`` —
        the deterministic section of a campaign report."""
        return [
            s.record()
            for s in sorted(self.specs, key=lambda s: (s.point, s.at_hit))
        ]

    @staticmethod
    def from_seed(
        seed: int,
        faults: int = 25,
        points: Optional[Dict[str, Tuple[str, ...]]] = None,
        kinds: Optional[Tuple[str, ...]] = None,
        max_hit: int = 6,
        windows: Optional[Dict[str, int]] = None,
        delay_s: float = 0.02,
        wedge_s: float = 1.0,
    ) -> "FaultSchedule":
        """Draw ``faults`` specs deterministically from ``seed``.

        The first draws guarantee KIND COVERAGE: one fault of every
        requested kind lands at a compatible point before the remainder
        fills in uniformly, so even a small campaign spans crash /
        wedge / corrupt / ENOSPC / delay. ``max_hit`` bounds the hit
        window per point (``windows`` overrides it per point — rare
        seams like the fleet barrier only see a few hits per campaign,
        so their faults must land early); the storm paces each leg
        until its points' armed cells have all fired, so low windows
        keep campaigns short.
        """
        points = dict(points if points is not None else INJECTION_POINTS)
        kinds = tuple(kinds if kinds is not None else FAULT_KINDS)
        windows = dict(windows or {})
        # Each (point, hit) cell holds at most one fault: more faults
        # than cells can never be drawn — fail loudly instead of
        # spinning the draw loop forever.
        capacity = sum(windows.get(p, max_hit) for p in points)
        if faults > capacity:
            raise ValueError(
                f"cannot arm {faults} faults over {len(points)} points "
                f"with {capacity} (point, hit) cells — raise max_hit/"
                "windows or lower the fault count"
            )
        rng = random.Random(int(seed))
        point_names = sorted(points)
        used: set = set()
        specs: List[FaultSpec] = []

        def draw(kind: str) -> Optional[FaultSpec]:
            compatible = [p for p in point_names if kind in points[p]]
            if not compatible:
                return None
            for _ in range(64):  # bounded re-draw over free cells
                point = rng.choice(compatible)
                at_hit = rng.randint(1, windows.get(point, max_hit))
                if (point, at_hit) in used:
                    continue
                used.add((point, at_hit))
                seconds = 0.0
                if kind == "delay":
                    seconds = round(rng.uniform(0.5, 1.5) * delay_s, 4)
                elif kind == "wedge":
                    seconds = round(rng.uniform(1.0, 1.5) * wedge_s, 4)
                return FaultSpec(point, kind, at_hit, seconds)
            return None

        for kind in kinds:  # coverage pass: one of each kind first
            if len(specs) >= faults:
                break
            spec = draw(kind)
            if spec is not None:
                specs.append(spec)
        misses = 0
        while len(specs) < faults:
            spec = draw(rng.choice(kinds))
            if spec is None:
                # Kind-compatible cells can exhaust before total
                # capacity does (e.g. every crash-capable cell full) —
                # bounded misses turn "stuck" into a loud error.
                misses += 1
                if misses > 64 * max(1, len(kinds)):
                    raise ValueError(
                        f"schedule draw exhausted after {len(specs)} of "
                        f"{faults} faults: no free cells for the "
                        f"requested kinds {kinds} — raise max_hit/"
                        "windows or lower the fault count"
                    )
                continue
            misses = 0
            specs.append(spec)
        return FaultSchedule(specs, seed=int(seed))


class FaultPlane:
    """Per-point hit counters plus the armed fault cells.

    ``hit`` is the only hot call: disabled, it returns after one
    attribute read; enabled-but-idle, it bumps one counter under a lock
    and returns. Firing is rare by construction.
    """

    def __init__(self, enabled: bool = False) -> None:
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._armed: Dict[Tuple[str, int], FaultSpec] = {}  # graftlock: guarded-by=_lock
        self._hits: Dict[str, int] = {}  # graftlock: guarded-by=_lock
        #: Fired faults, in firing order: dicts with the spec record
        #: plus a monotonic ``t`` (the storm's MTTR anchor).
        self.fired: List[dict] = []  # graftlock: guarded-by=_lock

    # -- arming ----------------------------------------------------------

    def arm(self, schedule: FaultSchedule) -> None:
        with self._lock:
            for spec in schedule.specs:
                self._armed[(spec.point, spec.at_hit)] = spec

    def disarm(self) -> None:
        """Drop every armed-but-unfired fault (teardown between legs)."""
        with self._lock:
            self._armed.clear()

    def reset(self) -> None:
        """Fresh campaign: counters, armed cells, firing log all clear."""
        with self._lock:
            self._armed.clear()
            self._hits.clear()
            del self.fired[:]

    def pending(self, points: Optional[Tuple[str, ...]] = None) -> int:
        """Armed-but-unfired fault count (optionally for a point
        subset) — the storm's pacing signal."""
        with self._lock:
            if points is None:
                return len(self._armed)
            wanted = set(points)
            return sum(1 for p, _ in self._armed if p in wanted)

    def armed_record(self) -> List[dict]:
        """Still-armed cells, sorted — chaos_violation incident context."""
        with self._lock:
            specs = sorted(
                self._armed.values(), key=lambda s: (s.point, s.at_hit)
            )
        return [s.record() for s in specs]

    def fired_record(self) -> List[dict]:
        """Fired faults sorted by ``(point, at_hit)`` — deterministic
        across replays whenever every armed fault fired (firing ORDER
        across points is thread timing; the sorted set is not)."""
        with self._lock:
            fired = list(self.fired)
        return sorted(
            (
                {k: v for k, v in f.items() if k != "t"}
                for f in fired
            ),
            key=lambda f: (f["point"], f["at_hit"]),
        )

    # -- the hot call ----------------------------------------------------

    def hit(self, point: str, path: Optional[Any] = None) -> None:
        """One occurrence of ``point``. Fires the armed fault for this
        hit index, if any. ``path`` is the file the seam is touching —
        required context for the corrupt kinds."""
        if not self.enabled:
            return
        with self._lock:
            n = self._hits.get(point, 0) + 1
            self._hits[point] = n
            spec = self._armed.pop((point, n), None)
            if spec is not None:
                self.fired.append(
                    {**spec.record(), "t": time.perf_counter()}
                )
        if spec is not None:
            self._fire(spec, path)

    # -- effects ---------------------------------------------------------

    @staticmethod
    def _fire(spec: FaultSpec, path: Optional[Any]) -> None:
        kind = spec.kind
        if kind == "raise":
            raise InjectedFault(
                f"injected fault at {spec.point} (hit {spec.at_hit})"
            )
        if kind == "enospc":
            raise OSError(
                errno.ENOSPC,
                f"No space left on device (injected at {spec.point})",
            )
        if kind in ("delay", "wedge"):
            time.sleep(spec.seconds)
            return
        if kind == "crash":
            raise SimulatedCrash(
                f"simulated crash at {spec.point} (hit {spec.at_hit})"
            )
        if kind in FILE_KINDS:
            if path is None:
                return  # point passed no file; recorded as fired anyway
            _corrupt_file(os.fspath(path), kind)
            return
        raise AssertionError(f"unhandled fault kind {kind!r}")


def _corrupt_file(path: str, kind: str) -> None:
    """Silent on-media damage: truncate to half, or flip one mid-file
    bit — both invisible to the rename-is-publication protocol, which is
    exactly why restore needs the checksum footer."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size == 0:
        return
    if kind == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        return
    with open(path, "r+b") as f:  # bitflip
        offset = size // 2
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ 0x40]) if byte else b"\x40")


# ----------------------------------------------------------------------
# Process-global plane
# ----------------------------------------------------------------------

_default_plane = FaultPlane(enabled=False)


def get_fault_plane() -> FaultPlane:
    """The process-global plane every injection point resolves at call
    time."""
    return _default_plane


def set_fault_plane(plane: FaultPlane) -> FaultPlane:
    """Swap the process-global plane (tests/campaigns); returns the
    previous one."""
    global _default_plane
    previous = _default_plane
    _default_plane = plane
    return previous


def configure_chaos(enabled: Optional[bool] = None) -> FaultPlane:
    """Re-shape the process-global plane in place (the entry points'
    ``chaos`` knob)."""
    plane = get_fault_plane()
    if enabled is not None:
        plane.enabled = bool(enabled)
    return plane


def fault_point(name: str, path: Optional[Any] = None) -> None:
    """Declare one occurrence of injection point ``name``.

    THE call production seams make. Disabled (the shipped default) it
    costs one global load + one attribute read + return, so points stay
    wired unconditionally — the same discipline that keeps tracer spans
    and registry counters in the hot paths. Host-side only: graftlint
    rule 19 rejects this call inside a traced scope.
    """
    plane = _default_plane
    if not plane.enabled:
        return
    plane.hit(name, path=path)
