"""Invariant checkers: what must still be true after a chaos campaign.

Each checker is a pure function over campaign artifacts (served-step
samples, probe outcomes, compile receipts, ``promotions.jsonl``, a
checkpoint directory) returning a list of :class:`Violation` — empty
means the invariant held through whatever the fault schedule did.
:func:`report_violations` is the alarm half: every tripped checker
becomes a ``chaos_violation`` flight-recorder incident carrying the
recent span history plus the armed/fired fault schedule as structured
context, so a failing campaign is diagnosable from its artifacts alone
(no re-run, no debugger).

The invariants are the ones PRs 4-11 individually earned, restated so
one campaign exercises them all (ROADMAP item 1 wants exactly this
restating before the fleet crosses the host boundary):

- **step monotonicity** — ``model_step`` never goes backward in
  response order, except across an audited rollback;
- **no accepted request lost** — every admitted request resolves
  (result or typed error), none wedge forever;
- **budget-1 compile receipts** — the gate's matrix program and every
  serving rung compile at most once, faults or no faults;
- **audit-log consistency** — ``promotions.jsonl`` parses, promoted
  steps ascend, rollbacks demote to previously-promoted steps,
  superseded candidates never serve;
- **checkpoint-dir crash consistency** — every discoverable checkpoint
  is checksum-valid; torn writes are invisible (``.tmp``), corrupt
  files are quarantined aside, never served.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from marl_distributedformation_tpu.chaos.plane import (
    FaultPlane,
    get_fault_plane,
)


@dataclasses.dataclass
class Violation:
    """One tripped invariant."""

    invariant: str
    detail: str
    context: Optional[dict] = None

    def record(self) -> dict:
        out = {"invariant": self.invariant, "detail": self.detail}
        if self.context:
            out["context"] = dict(self.context)
        return out


def check_step_monotonic(
    samples: Sequence[Tuple[float, int]],
    rollback_to_steps: Sequence[int] = (),
) -> List[Violation]:
    """``model_step`` over response order must never decrease — except a
    decrease landing exactly on an audited rollback target (the
    monotonicity-exempt pinned demotion). ``samples`` are ``(t, step)``
    in response order."""
    violations: List[Violation] = []
    allowed = set(int(s) for s in rollback_to_steps)
    prev: Optional[int] = None
    for t, step in samples:
        step = int(step)
        if prev is not None and step < prev and step not in allowed:
            violations.append(
                Violation(
                    "step_monotonic",
                    f"served step went backward {prev} -> {step} with no "
                    "audited rollback to that step",
                    {"t": t, "from_step": prev, "to_step": step},
                )
            )
        prev = step
    return violations


def check_no_request_lost(
    outcomes: Sequence[Dict[str, Any]],
) -> List[Violation]:
    """Every accepted request must RESOLVE — a success, or a typed
    error the caller can act on. ``outcomes`` are
    ``{"ok": bool, "error": str|None, "hung": bool}`` per accepted
    request (the storm's prober fills them); a hung future is the
    violation this checker exists for."""
    violations = []
    hung = [o for o in outcomes if o.get("hung")]
    if hung:
        violations.append(
            Violation(
                "no_request_lost",
                f"{len(hung)} accepted request(s) never resolved "
                "(future wedged past its deadline + slack)",
                {"hung": len(hung), "total": len(outcomes)},
            )
        )
    return violations


def check_budget_one(compiles: Dict[str, int]) -> List[Violation]:
    """Every named program's compile count must be <= 1 — the budget-1
    receipts must hold with chaos armed (graftlint rule 19 is the
    static half of this guarantee)."""
    violations = []
    for name, count in sorted(compiles.items()):
        if int(count) > 1:
            violations.append(
                Violation(
                    "budget_one",
                    f"program {name!r} compiled {count} times under "
                    "chaos (budget is 1)",
                    {"program": name, "compiles": int(count)},
                )
            )
    return violations


# Events that terminate a candidate's journey vs. annotate it.
_AUDIT_EVENTS = frozenset({
    "promoted", "rejected", "rolled_back", "rollback_failed",
    "promotion_deferred", "promotion_superseded", "curriculum_updated",
    "curriculum_update_failed", "candidate_vanished",
})


def check_audit_log(path: str | Path) -> List[Violation]:
    """``promotions.jsonl`` must read back as a consistent state
    machine: known events, promoted steps strictly ascending, every
    rollback demoting to a step that actually served (a previously
    promoted step), and no superseded candidate later claimed as
    promoted."""
    from marl_distributedformation_tpu.pipeline.promote import PromotionLog

    violations: List[Violation] = []
    try:
        records = PromotionLog.read(path)
    except Exception as e:  # noqa: BLE001 — unparseable log IS the trip
        return [
            Violation(
                "audit_log", f"promotions.jsonl unreadable: {e!r}",
                {"path": str(path)},
            )
        ]
    promoted_steps: List[int] = []
    superseded: set = set()
    for i, rec in enumerate(records):
        event = rec.get("event")
        if event not in _AUDIT_EVENTS:
            violations.append(
                Violation(
                    "audit_log",
                    f"line {i}: unknown event {event!r}",
                    {"line": i},
                )
            )
            continue
        step = rec.get("step")
        if event == "promoted":
            if step in superseded:
                violations.append(
                    Violation(
                        "audit_log",
                        f"line {i}: step {step} promoted AFTER being "
                        "superseded — a never-served candidate became "
                        "the baseline",
                        {"line": i, "step": step},
                    )
                )
            if promoted_steps and step <= promoted_steps[-1]:
                violations.append(
                    Violation(
                        "audit_log",
                        f"line {i}: promoted step {step} does not ascend "
                        f"past {promoted_steps[-1]}",
                        {"line": i, "step": step},
                    )
                )
            promoted_steps.append(step)
        elif event == "promotion_superseded":
            superseded.add(step)
        elif event == "rolled_back":
            to_step = rec.get("to_step")
            if to_step not in promoted_steps:
                violations.append(
                    Violation(
                        "audit_log",
                        f"line {i}: rolled back to step {to_step}, which "
                        "was never promoted",
                        {"line": i, "to_step": to_step},
                    )
                )
    return violations


def check_checkpoint_dir(log_dir: str | Path) -> List[Violation]:
    """Crash consistency of a checkpoint directory: every DISCOVERABLE
    file (the ``.msgpack``-suffixed names ``latest_checkpoint`` /
    ``CheckpointDiscovery`` would serve) must carry a valid checksum
    footer; torn ``.tmp`` files and quarantined (``.quarantined``)
    files are invisible to discovery and therefore fine."""
    from marl_distributedformation_tpu.utils.checkpoint import (
        CorruptCheckpointError,
        read_checkpoint_payload,
    )

    violations: List[Violation] = []
    log_dir = Path(log_dir)
    if not log_dir.is_dir():
        return violations
    for p in sorted(log_dir.iterdir()):
        if p.suffix != ".msgpack" or p.name.startswith("."):
            continue  # invisible to discovery: torn tmp, quarantined
        try:
            read_checkpoint_payload(p, quarantine=False)
        except CorruptCheckpointError as e:
            violations.append(
                Violation(
                    "checkpoint_crash_consistency",
                    f"discoverable checkpoint {p.name} is corrupt and "
                    f"was never quarantined: {e}",
                    {"path": str(p)},
                )
            )
        except OSError as e:
            violations.append(
                Violation(
                    "checkpoint_crash_consistency",
                    f"discoverable checkpoint {p.name} unreadable: {e!r}",
                    {"path": str(p)},
                )
            )
    return violations


def check_finite_checkpoints(log_dir: str | Path) -> List[Violation]:
    """Train-lane invariant (docs/recovery.md): no DISCOVERABLE
    checkpoint may carry non-finite float leaves — the write gate
    (utils/checkpoint.py) must have skipped every poisoned snapshot
    before it reached a ``rl_model_*`` name. Corrupt files are the
    crash-consistency checker's business; this one restores each valid
    file and walks its floats."""
    from marl_distributedformation_tpu.utils.checkpoint import (
        CorruptCheckpointError,
        msgpack_restore_file,
        nonfinite_leaf,
    )

    violations: List[Violation] = []
    log_dir = Path(log_dir)
    if not log_dir.is_dir():
        return violations
    for p in sorted(log_dir.iterdir()):
        if p.suffix != ".msgpack" or p.name.startswith("."):
            continue
        try:
            tree = msgpack_restore_file(p, quarantine=False)
        except (CorruptCheckpointError, OSError):
            continue  # check_checkpoint_dir owns damage
        bad = nonfinite_leaf(tree)
        if bad is not None:
            violations.append(
                Violation(
                    "nonfinite_checkpoint",
                    f"discoverable checkpoint {p.name} carries "
                    f"non-finite values at {bad} — a diverged state "
                    "became visible to discovery (the write gate "
                    "failed)",
                    {"path": str(p), "leaf": bad},
                )
            )
    return violations


def check_final_params_finite(params: Any) -> List[Violation]:
    """The run must END on finite params, whatever the fault schedule
    did mid-flight — the recovery ladder's terminal guarantee."""
    from marl_distributedformation_tpu.utils.checkpoint import (
        nonfinite_leaf,
    )

    bad = nonfinite_leaf(params)
    if bad is None:
        return []
    return [
        Violation(
            "finite_final_params",
            f"the run terminated with non-finite params at {bad} — the "
            "recovery ladder failed to restore a last-good state",
            {"leaf": bad},
        )
    ]


def check_recovery_log(
    path: str | Path,
    max_rollbacks: Optional[int] = None,
    mttr_bound_s: Optional[float] = None,
) -> List[Violation]:
    """``recovery.jsonl`` must read back as a consistent ladder history:
    schema-valid lines (train.recovery.read_recovery_log), rollback
    counters strictly ascending, every MTTR finite and positive (and
    under ``mttr_bound_s`` when given — recovery must be BOUNDED, not
    just eventual), a ``halt`` only as the final event, and no more
    rollbacks than the configured budget."""
    import math

    from marl_distributedformation_tpu.train.recovery import (
        read_recovery_log,
    )

    violations: List[Violation] = []
    try:
        records = read_recovery_log(path)
    except ValueError as e:
        return [
            Violation(
                "recovery_log", f"recovery.jsonl invalid: {e}",
                {"path": str(path)},
            )
        ]
    last_recoveries = 0
    for i, rec in enumerate(records):
        event = rec.get("event")
        if event == "rollback":
            n = int(rec["recoveries"])
            if n != last_recoveries + 1:
                violations.append(
                    Violation(
                        "recovery_log",
                        f"line {i}: rollback counter jumped "
                        f"{last_recoveries} -> {n} (must ascend by 1)",
                        {"line": i},
                    )
                )
            last_recoveries = n
            if max_rollbacks is not None and n > max_rollbacks:
                violations.append(
                    Violation(
                        "recovery_log",
                        f"line {i}: {n} rollbacks exceed the configured "
                        f"budget of {max_rollbacks}",
                        {"line": i},
                    )
                )
            mttr = rec["mttr_s"]
            # Already-parsed JSON numbers: no float() pull (rule 22's
            # probe-over-extraction pattern is for device values).
            if not (
                isinstance(mttr, (int, float))
                and math.isfinite(mttr)
                and mttr > 0.0
            ):
                violations.append(
                    Violation(
                        "recovery_mttr",
                        f"line {i}: rollback MTTR {mttr!r} is not a "
                        "finite number > 0",
                        {"line": i},
                    )
                )
            elif mttr_bound_s is not None and float(mttr) > mttr_bound_s:
                violations.append(
                    Violation(
                        "recovery_mttr",
                        f"line {i}: rollback MTTR {float(mttr):.3f}s "
                        f"exceeds the {mttr_bound_s}s bound — recovery "
                        "must be bounded, not merely eventual",
                        {"line": i},
                    )
                )
        elif event == "halt" and i != len(records) - 1:
            violations.append(
                Violation(
                    "recovery_log",
                    f"line {i}: 'halt' is terminal but "
                    f"{len(records) - 1 - i} event(s) follow it",
                    {"line": i},
                )
            )
    return violations


def check_no_duplicate_consume(
    consumed_seqs: Sequence[int],
) -> List[Violation]:
    """Sebulba transfer contract (docs/sebulba.md): no trajectory batch
    is ever consumed twice. ``consumed_seqs`` is the TransferQueue's
    consume-order artifact; the chaos ``sebulba.dequeue`` seam redelivers
    items, so the queue's seq guard must leave this STRICTLY increasing
    — a repeat or regression means a duplicate reached the learner
    (the same batch counted into two updates)."""
    violations: List[Violation] = []
    prev: Optional[int] = None
    for i, seq in enumerate(consumed_seqs):
        seq = int(seq)
        if prev is not None and seq <= prev:
            violations.append(
                Violation(
                    "no_duplicate_consume",
                    f"consume order position {i}: seq {seq} after {prev} "
                    "— a redelivered trajectory batch reached the "
                    "learner twice (the queue's seq guard failed)",
                    {"position": i, "seq": seq, "prev": prev},
                )
            )
        prev = seq
    return violations


def check_params_version_monotone(
    consumed_versions: Sequence[int],
) -> List[Violation]:
    """Sebulba params contract: the ``params_version`` stamped on
    consumed batches never goes BACKWARD — the ParamBus is single-slot
    latest-wins, so an actor can act on stale params (dropped publish)
    but never on a version older than one it already acted with. A
    regression here means the bus swapped backward or a stale batch
    outlived the staleness gate out of order."""
    violations: List[Violation] = []
    prev: Optional[int] = None
    for i, version in enumerate(consumed_versions):
        version = int(version)
        if prev is not None and version < prev:
            violations.append(
                Violation(
                    "params_version_monotone",
                    f"consume order position {i}: params_version "
                    f"{version} after {prev} — the latest-wins bus "
                    "regressed (an older snapshot overwrote a newer one)",
                    {"position": i, "version": version, "prev": prev},
                )
            )
        prev = version
    return violations


def check_bounded_staleness(
    staleness_samples: Sequence[int],
    max_param_staleness: int,
) -> List[Violation]:
    """Sebulba staleness contract: every batch the learner CONSUMED was
    acted with params at most ``max_param_staleness`` updates behind the
    learner's current version — the driver's staleness gate must drop
    (never train on) anything older, even while the chaos
    ``sebulba.param_publish`` seam is holding publishes back."""
    violations: List[Violation] = []
    bound = int(max_param_staleness)
    for i, staleness in enumerate(staleness_samples):
        staleness = int(staleness)
        if staleness > bound:
            violations.append(
                Violation(
                    "bounded_staleness",
                    f"consumed batch {i} was acted {staleness} params "
                    f"versions behind the learner (bound: {bound}) — "
                    "the staleness gate let an over-stale trajectory "
                    "into an update",
                    {"position": i, "staleness": staleness, "bound": bound},
                )
            )
    return violations


def report_violations(
    violations: Sequence[Violation],
    plane: Optional[FaultPlane] = None,
    trace_id: Optional[str] = None,
) -> List[dict]:
    """Alarm every violation: one ``chaos_violation`` incident per trip,
    dumping the recent span history PLUS the armed/fired fault schedule
    as structured flight-recorder context — the campaign's postmortem
    writes itself. Returns the violation records (the report's
    ``chaos_violations`` list). Never raises."""
    from marl_distributedformation_tpu.obs import get_registry, get_tracer

    plane = plane if plane is not None else get_fault_plane()
    tracer = get_tracer()
    registry = get_registry()
    records = []
    for v in violations:
        records.append(v.record())
        registry.counter("chaos_invariant_violations_total").inc()
        tracer.incident(
            "chaos_violation",
            trace_id=trace_id,
            invariant=v.invariant,
            detail=v.detail,
            violation_context=v.context or {},
            fault_schedule_armed=plane.armed_record(),
            fault_schedule_fired=plane.fired_record(),
        )
    return records
