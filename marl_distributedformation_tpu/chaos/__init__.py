"""Chaos plane: deterministic fault injection, invariant checking, and
self-healing supervision (docs/chaos.md).

- :mod:`.plane` — the :class:`FaultPlane` and its seeded
  :class:`FaultSchedule`; production seams call :func:`fault_point`
  (one attribute read when disabled; graftlint rule 19 keeps it out of
  traced scopes).
- :mod:`.invariants` — pure checkers over campaign artifacts (step
  monotonicity, no-request-lost, budget-1 receipts, audit-log and
  checkpoint-dir consistency) plus the ``chaos_violation`` flight-
  recorder alarm.
- :mod:`.watchdog` — heartbeat-driven lane supervision with capped-
  backoff restarts.

``scripts/chaos_storm.py`` runs trainer -> gate -> fleet under a seeded
campaign and reports MTTR + violations as one JSON line.
"""

from marl_distributedformation_tpu.chaos.invariants import (
    Violation,
    check_audit_log,
    check_bounded_staleness,
    check_budget_one,
    check_checkpoint_dir,
    check_final_params_finite,
    check_finite_checkpoints,
    check_no_duplicate_consume,
    check_no_request_lost,
    check_params_version_monotone,
    check_recovery_log,
    check_step_monotonic,
    report_violations,
)
from marl_distributedformation_tpu.chaos.plane import (
    DISRUPTIVE_KINDS,
    FAULT_KINDS,
    INJECTION_POINTS,
    FaultPlane,
    FaultSchedule,
    FaultSpec,
    InjectedFault,
    SimulatedCrash,
    configure_chaos,
    fault_point,
    get_fault_plane,
    set_fault_plane,
)
from marl_distributedformation_tpu.chaos.watchdog import (
    Heartbeat,
    Lane,
    LaneWatchdog,
)

__all__ = [
    "DISRUPTIVE_KINDS",
    "FAULT_KINDS",
    "INJECTION_POINTS",
    "FaultPlane",
    "FaultSchedule",
    "FaultSpec",
    "Heartbeat",
    "InjectedFault",
    "Lane",
    "LaneWatchdog",
    "SimulatedCrash",
    "Violation",
    "check_audit_log",
    "check_bounded_staleness",
    "check_budget_one",
    "check_checkpoint_dir",
    "check_final_params_finite",
    "check_finite_checkpoints",
    "check_no_duplicate_consume",
    "check_no_request_lost",
    "check_params_version_monotone",
    "check_recovery_log",
    "check_step_monotonic",
    "configure_chaos",
    "fault_point",
    "get_fault_plane",
    "report_violations",
    "set_fault_plane",
]
