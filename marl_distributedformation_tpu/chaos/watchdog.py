"""LaneWatchdog: self-healing supervision for long-lived host lanes.

An always-learning process is a handful of daemon threads (the pipeline
supervision loop, the reload watcher, the scheduler workers), and until
now a lane that DIED (an uncontained exception, a simulated kill) or
WEDGED (a hung device op, an injected sleep) simply stopped doing its
job — silently, forever. The watchdog closes that gap:

- every supervised lane **heartbeats** into the MetricsRegistry
  (``{lane}_heartbeat_age_s`` is scrapeable like every other gauge), so
  "is the control plane alive" is a metrics question, not a debugger
  question;
- the watchdog thread samples each lane: a dead thread or a heartbeat
  older than ``wedge_timeout_s`` triggers a **restart** through the
  lane's own ``restart`` callable, with capped exponential backoff
  between attempts (a lane that dies instantly on every start must not
  spin the process);
- every restart bumps ``pipeline_restarts_total`` and dumps a
  ``lane_restart`` flight record — a self-healed wedge still leaves a
  postmortem trail.

A wedged thread cannot be killed in CPython; restarting means
ABANDONING it (the lane owner hands out a fresh generation token — see
``AlwaysLearningPipeline.restart_loop``) and starting a replacement.
The abandoned thread exits at its next generation check.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from marl_distributedformation_tpu.obs import get_registry, get_tracer


class Heartbeat:
    """One lane's liveness pulse. ``beat()`` is the lane's per-iteration
    call: one monotonic stamp plus one registry gauge set."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._last = time.monotonic()

    def beat(self) -> None:
        self._last = time.monotonic()
        get_registry().gauge(f"{self.name}_heartbeat_age_s").set(0.0)

    def age_s(self) -> float:
        return time.monotonic() - self._last


@dataclasses.dataclass
class Lane:
    """One supervised lane: how to probe it and how to restart it.
    ``heartbeat=None`` supervises liveness only (a lane with no natural
    iteration cadence, like a scheduler worker that blocks on its
    queue, cannot beat — dead-thread detection still applies)."""

    name: str
    heartbeat: Optional[Heartbeat]
    is_alive: Callable[[], bool]
    restart: Callable[[], Any]
    restarts: int = 0  # cumulative, for reporting — never resets
    streak: int = 0  # consecutive restarts, drives backoff; heals to 0
    _last_restart: float = 0.0
    _healthy_since: float = 0.0


class LaneWatchdog:
    """Probe registered lanes; restart dead/wedged ones with capped
    exponential backoff.

    Args:
      wedge_timeout_s: a live thread whose heartbeat is older than this
        is wedged (size it past the longest legitimate iteration —
        e.g. one gate eval — or the watchdog will flap).
      backoff_base_s / backoff_cap_s: restart pacing. The Nth
        consecutive restart waits ``min(cap, base * 2**(N-1))`` after
        the previous one; a lane healthy for ``heal_after_s`` resets
        the streak.
      poll_interval_s: watchdog sampling cadence.
    """

    def __init__(
        self,
        wedge_timeout_s: float = 10.0,
        backoff_base_s: float = 0.5,
        backoff_cap_s: float = 30.0,
        heal_after_s: float = 30.0,
        poll_interval_s: float = 0.25,
    ) -> None:
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.heal_after_s = float(heal_after_s)
        self.poll_interval_s = float(poll_interval_s)
        self.lanes: Dict[str, Lane] = {}
        self.restart_log: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wiring ----------------------------------------------------------

    def register(
        self,
        name: str,
        heartbeat: Optional[Heartbeat],
        is_alive: Callable[[], bool],
        restart: Callable[[], Any],
    ) -> Lane:
        lane = Lane(
            name=name, heartbeat=heartbeat, is_alive=is_alive,
            restart=restart,
        )
        lane._healthy_since = time.monotonic()
        self.lanes[name] = lane
        return lane

    def watch_pipeline(self, pipeline: Any) -> Lane:
        """Supervise an ``AlwaysLearningPipeline``'s run loop (the lane
        the storm wedges): heartbeat from the loop body, restart via
        ``restart_loop`` (abandon-and-replace)."""
        return self.register(
            "pipeline_loop",
            pipeline.heartbeat,
            pipeline.loop_alive,
            pipeline.restart_loop,
        )

    def watch_fleet(self, router: Any) -> List[Lane]:
        """Supervise every replica's scheduler worker (liveness-only:
        a blocked-on-queue worker has no iteration cadence to beat).
        A crashed worker is restarted through
        ``MicroBatchScheduler.restart``; the router's half-open probe
        then readmits the healed replica into rotation — the fleet
        regrows to full width instead of bleeding replicas until
        ``NoHealthyReplicas``."""
        lanes = []
        for replica in router.replicas:
            scheduler = replica.scheduler
            lanes.append(
                self.register(
                    f"replica{replica.index}_worker",
                    None,
                    lambda s=scheduler: s.alive,
                    lambda s=scheduler: s.restart(),
                )
            )
        return lanes

    # -- supervision -----------------------------------------------------

    def restarts_total(self) -> int:
        return sum(lane.restarts for lane in self.lanes.values())

    def check_once(self) -> int:
        """One supervision sweep; returns restarts performed. Public so
        tests and the storm can drive supervision deterministically."""
        restarted = 0
        now = time.monotonic()
        for lane in self.lanes.values():
            age = 0.0
            if lane.heartbeat is not None:
                age = lane.heartbeat.age_s()
                get_registry().gauge(
                    f"{lane.heartbeat.name}_heartbeat_age_s"
                ).set(age)
            alive = True
            try:
                alive = bool(lane.is_alive())
            except Exception:  # noqa: BLE001 — a broken probe reads dead
                alive = False
            reason = None
            if not alive:
                reason = "lane thread dead"
            elif age > self.wedge_timeout_s:
                reason = (
                    f"heartbeat stale {age:.2f}s "
                    f"(wedge_timeout_s={self.wedge_timeout_s:g})"
                )
            if reason is None:
                if now - lane._healthy_since > self.heal_after_s:
                    lane.streak = 0  # streak heals: backoff resets
                continue
            lane._healthy_since = now
            backoff = min(
                self.backoff_cap_s,
                self.backoff_base_s * (2.0 ** max(0, lane.streak - 1)),
            )
            if lane.streak and now - lane._last_restart < backoff:
                continue  # backoff window: do not flap-restart
            restarted += self._restart(lane, reason)
        return restarted

    def _restart(self, lane: Lane, reason: str) -> int:
        entry = {
            "lane": lane.name,
            "reason": reason,
            "restarts": lane.restarts + 1,
            "time": time.time(),
        }
        try:
            lane.restart()
        except Exception as e:  # noqa: BLE001 — a failed restart is a
            # recorded incident, never a dead watchdog; backoff retries.
            entry["restart_error"] = repr(e)[:200]
        lane.restarts += 1
        lane.streak += 1
        lane._last_restart = time.monotonic()
        if lane.heartbeat is not None:
            lane.heartbeat.beat()  # grace: a fresh lane gets a full window
        self.restart_log.append(entry)
        registry = get_registry()
        registry.counter("pipeline_restarts_total").inc()
        registry.counter(f"lane_restarts_total_{lane.name}").inc()
        # Every self-heal leaves a postmortem trail: the ring still holds
        # the spans that led to the wedge/death.
        get_tracer().incident("lane_restart", **entry)
        return 1

    # -- background loop -------------------------------------------------

    def start(self) -> "LaneWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, name="lane-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10.0)
        self._thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — the supervisor of last
                pass  # resort must never die of its own probe

    def __enter__(self) -> "LaneWatchdog":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
