"""Serving observability: occupancy, latency percentiles, queue health.

Thread-safe accumulator the scheduler records into on its worker thread
while clients read snapshots from theirs. Snapshots are flat
``{name: float}`` dicts, shaped for ``utils.logging.MetricsLogger.log``
(JSONL/stdout/wandb/tensorboard) — serving gets the same observability
pipeline training already has, one record per ``emit_every`` batches
instead of one per request.

The numbers that matter, and why (docs/serving.md):

- ``batch_occupancy_pct`` — real rows / padded bucket capacity. The
  direct cost of the bucket ladder: low occupancy means the ladder is
  too coarse for the traffic (or the coalescing window too short).
- ``latency_p50/p95/p99_ms`` — enqueue-to-result, the client-visible
  number. p99 >> p50 usually means the queue is saturating (backpressure
  about to engage), not that the model got slower.
- ``queue_depth`` / ``rejected_total`` — backpressure health: depth
  rides near zero in a healthy server; rejects mean callers must honor
  ``retry_after_s``.
- ``model_swap_count`` — hot-reload liveness (a stuck watcher shows as
  a flat line while the trainer keeps writing checkpoints).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List


class ServingMetrics:
    def __init__(self, latency_window: int = 4096) -> None:
        self._lock = threading.Lock()
        self._latencies: Deque[float] = deque(maxlen=latency_window)  # graftlock: guarded-by=_lock
        self._batch_seconds: Deque[float] = deque(maxlen=256)  # graftlock: guarded-by=_lock
        self.requests_total = 0  # graftlock: guarded-by=_lock
        self.rows_total = 0  # graftlock: guarded-by=_lock
        self.batches_total = 0  # graftlock: guarded-by=_lock
        self.padded_rows_total = 0  # graftlock: guarded-by=_lock
        self.rejected_total = 0  # graftlock: guarded-by=_lock
        self.timeouts_total = 0  # graftlock: guarded-by=_lock
        self.preempted_total = 0  # graftlock: guarded-by=_lock — yielded batch slots
        self.queue_depth = 0  # graftlock: guarded-by=_lock

    # -- recording (scheduler side) -------------------------------------

    def record_submit(self, queue_depth: int) -> None:
        with self._lock:
            self.requests_total += 1
            self.queue_depth = queue_depth

    def record_reject(self) -> None:
        with self._lock:
            self.rejected_total += 1

    def record_timeout(self, n: int = 1) -> None:
        with self._lock:
            self.timeouts_total += n

    def record_preempted(self) -> None:
        """A queued batch-class request was evicted to admit an
        interactive one (scheduler SLO classes)."""
        with self._lock:
            self.preempted_total += 1

    def record_batch(
        self,
        rows: int,
        padded_rows: int,
        batch_seconds: float,
        latencies_s: List[float],
        queue_depth: int,
    ) -> None:
        with self._lock:
            self.batches_total += 1
            self.rows_total += rows
            self.padded_rows_total += padded_rows
            self._batch_seconds.append(batch_seconds)
            self._latencies.extend(latencies_s)
            self.queue_depth = queue_depth

    # -- reading ---------------------------------------------------------

    def latencies_snapshot(self) -> List[float]:
        """Copy of the recent latency window (seconds). The fleet
        aggregator merges these across replicas so fleet percentiles are
        computed over raw samples, not averaged per-replica percentiles
        (averaging percentiles is statistically meaningless)."""
        with self._lock:
            return list(self._latencies)

    def mean_batch_seconds(self, default: float = 1e-3) -> float:
        """Recent mean wall-clock per dispatched batch — the unit the
        scheduler prices ``retry_after_s`` in."""
        with self._lock:
            if not self._batch_seconds:
                return default
            return sum(self._batch_seconds) / len(self._batch_seconds)

    @staticmethod
    def _percentile(ordered: List[float], q: float) -> float:
        if not ordered:
            return 0.0
        # Nearest-rank on the sorted window: cheap, monotone, and exact
        # at the tails (p99 of 100 samples is the 99th largest, not an
        # interpolation past the data).
        idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
        return ordered[int(idx)]

    def snapshot(self) -> Dict[str, float]:
        """Flat float dict for ``MetricsLogger.log`` / the smoke bench."""
        with self._lock:
            ordered = sorted(self._latencies)
            occupancy = (
                100.0 * self.rows_total / self.padded_rows_total
                if self.padded_rows_total
                else 0.0
            )
            return {
                "requests": float(self.requests_total),
                "rows": float(self.rows_total),
                "batches": float(self.batches_total),
                "batch_occupancy_pct": occupancy,
                "mean_rows_per_batch": (
                    self.rows_total / self.batches_total
                    if self.batches_total
                    else 0.0
                ),
                "latency_p50_ms": 1e3 * self._percentile(ordered, 0.50),
                "latency_p95_ms": 1e3 * self._percentile(ordered, 0.95),
                "latency_p99_ms": 1e3 * self._percentile(ordered, 0.99),
                "queue_depth": float(self.queue_depth),
                "rejected_total": float(self.rejected_total),
                "timeouts_total": float(self.timeouts_total),
                "batch_preempted_total": float(self.preempted_total),
            }
