"""Mesh-sliced inference: serve the big rungs sharded, not replicated.

The fleet (serving/fleet/) scales by REPLICATION — every replica holds a
full param copy and full bucket ladder, so per-device memory caps the
model size and the big rungs burn one whole device each. This module is
the other scaling axis from ROADMAP item 3: one engine whose compiled
rungs run over a device-mesh *slice*, with

- **partition-rule-driven placement** (the `match_partition_rules` /
  `make_shard_and_gather_fns` idiom): a list of ``(regex, PartitionSpec)``
  rules maps every param leaf — by its ``/``-joined tree path — to a
  mesh layout, and the derived shard fns place the tree ON the mesh
  exactly once (at engine build and at reload commit, never per call);
- **batch-axis request sharding**: the padded request buffer is placed
  ``P("dp")`` so each mesh device computes its block of rows. With
  replicated params that is classic data-parallel inference — the
  per-row math is IDENTICAL to the single-device program, which is why
  the sharded==replicated parity gate is *bitwise* at f32, not a
  tolerance;
- an optional ``"mp"`` mesh axis for rules that split wide kernels over
  their OUTPUT feature axis (contraction dim intact — no reduction
  reordering, parity stays bitwise). Rules whose axes the mesh lacks, or
  whose dims don't divide, degrade to replication per-leaf instead of
  failing: one rule set serves every mesh shape.

The engine keeps the whole ``BucketedPolicyEngine`` contract (bucket
ladder, budget-1 RetraceGuards, fold_in keys, traced ``deterministic``),
so the fleet router can treat it as one more replica — the routing layer
sends big-rung requests here and keeps small rungs on the cheap
single-device replicas (serving/fleet/router.py).
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from marl_distributedformation_tpu.analysis.guards import (
    register_aot_program,
)
from marl_distributedformation_tpu.obs.ledger import get_ledger
from marl_distributedformation_tpu.serving.engine import BucketedPolicyEngine

# Default rules for this repo's actor-critic family: tower kernels may
# split over an "mp" axis on their OUTPUT features (bias splits with
# them); scalars and everything unmatched replicate. On a dp-only mesh
# every rule degrades to P() — pure data parallelism.
DEFAULT_PARTITION_RULES: Tuple[Tuple[str, P], ...] = (
    ("log_std", P()),
    (r"(pi|vf)_\d+/kernel", P(None, "mp")),
    (r"(pi|vf)_\d+/bias", P("mp")),
    (r".*", P()),
)

DEFAULT_SHARDED_BUCKETS = (64, 512)


def _tree_paths(tree: Any, sep: str = "/") -> List[Tuple[str, Any]]:
    """Flatten a pytree into ``(joined_path, leaf)`` pairs — the name a
    partition rule matches against (dict keys joined by ``sep``)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for entry in path:
            key = getattr(entry, "key", None)
            if key is None:
                key = getattr(entry, "idx", None)
            parts.append(str(key))
        out.append((sep.join(parts), leaf))
    return out


def fit_spec_to_mesh(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Degrade a PartitionSpec to what ``mesh`` and ``shape`` support:
    axes the mesh doesn't have, or whose mesh size doesn't divide the
    dim, fall back to ``None`` (replicated on that dim). Keeps one rule
    set valid across every mesh topology and every head width."""
    axes = []
    for i, ax in enumerate(tuple(spec)):
        ok = (
            ax is not None
            and ax in mesh.shape
            and i < len(shape)
            and shape[i] % mesh.shape[ax] == 0
        )
        axes.append(ax if ok else None)
    while axes and axes[-1] is None:
        axes.pop()
    return P(*axes)


def match_partition_rules(
    rules: Sequence[Tuple[str, P]], params: Any, mesh: Mesh
) -> Any:
    """Pytree of PartitionSpec from ``(regex, spec)`` rules, matched
    against each leaf's ``/``-joined path (first match wins — the
    fmengine/EasyLM idiom). Scalars never partition; matched specs are
    fitted to the mesh (see :func:`fit_spec_to_mesh`). Raises when no
    rule matches a leaf — ship a catch-all as the last rule."""

    def spec_for(name: str, leaf: Any) -> P:
        shape = tuple(np.shape(leaf))
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for pattern, spec in rules:
            if re.search(pattern, name) is not None:
                return fit_spec_to_mesh(spec, shape, mesh)
        raise ValueError(f"no partition rule matched param {name!r}")

    named = {n: spec_for(n, leaf) for n, leaf in _tree_paths(params)}
    leaves = [named[n] for n, _ in _tree_paths(params)]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def make_shard_and_gather_fns(
    specs: Any, mesh: Mesh
) -> Tuple[Any, Any]:
    """Pytrees of per-leaf shard / gather callables from a spec tree.

    ``shard_fn(leaf)`` places the leaf on the mesh under its
    NamedSharding — called ONCE per placement event (engine build,
    reload commit), never on the request path. ``gather_fn(leaf)``
    brings a mesh-resident leaf back to one host array (checkpointing /
    debugging — serving never gathers params)."""

    def _make(spec: P):
        sharding = NamedSharding(mesh, spec)

        def shard_fn(leaf: Any) -> Any:
            return jax.device_put(leaf, sharding)

        def gather_fn(leaf: Any) -> np.ndarray:
            return np.asarray(jax.device_get(leaf))

        return shard_fn, gather_fn

    # PartitionSpec is tuple-shaped — without is_leaf, tree_map would
    # recurse INTO each spec (and an empty P() would flatten to nothing).
    pairs = jax.tree_util.tree_map(
        _make, specs, is_leaf=lambda x: isinstance(x, P)
    )
    shard_fns = jax.tree_util.tree_map(
        lambda p: p[0], pairs, is_leaf=lambda x: isinstance(x, tuple)
    )
    gather_fns = jax.tree_util.tree_map(
        lambda p: p[1], pairs, is_leaf=lambda x: isinstance(x, tuple)
    )
    return shard_fns, gather_fns


@dataclasses.dataclass(frozen=True)
class ShardedSpec:
    """How a fleet builds its mesh-backed big-rung engine.

    ``axis_sizes`` follows ``parallel.mesh.make_mesh`` (``{"dp": -1}``
    = every local device on the batch axis). ``min_rows`` is the routing
    threshold: requests with at least this many rows prefer the sharded
    engine; smaller ones stay on the single-device replicas. ``dtype``
    opts the sharded rungs into bf16. ``window_ms`` is the slice's own
    coalescing window (``None`` inherits the fleet's): a dedicated lane
    whose routing floor fills its smallest rung has nothing to coalesce,
    so the autotuner emits 0.0 there (``LadderPlan.sharded_window_ms``)
    — waiting would be pure added latency on every big request."""

    axis_sizes: Optional[Dict[str, int]] = None
    buckets: Tuple[int, ...] = DEFAULT_SHARDED_BUCKETS
    min_rows: Optional[int] = None
    dtype: Optional[str] = None
    rules: Tuple[Tuple[str, P], ...] = DEFAULT_PARTITION_RULES
    window_ms: Optional[float] = None

    @property
    def route_min_rows(self) -> int:
        return self.min_rows if self.min_rows else min(self.buckets)

    def evolved(self, **changes: object) -> "ShardedSpec":
        """A new spec with ``changes`` applied — the delta form the
        elastic controller hands the fleet when it re-derives only part
        of the slice config (say, new ``buckets`` from a retune while
        the mesh axes stay put). Unknown fields raise, same as
        ``dataclasses.replace``."""
        return dataclasses.replace(self, **changes)


class ShardedPolicyEngine(BucketedPolicyEngine):
    """``BucketedPolicyEngine`` whose rungs run over a device-mesh slice.

    Same compiled-path contract as the base engine (one compile per
    rung, ever; params an argument, not a constant), with placement
    changed from "one device" to "one mesh": params live under their
    partition-rule shardings (placed once — at construction here, at
    the barrier commit by the fleet coordinator), the padded request
    buffer enters under the ``P("dp")`` batch layout (fresh data HAS
    to cross the host boundary; the graftlint rule-16 hazard is
    re-placing *params* per call), and each rung runs as an AOT
    executable lowered once against those committed layouts — steady
    state hands the host buffer straight to the executable, so the
    request path carries no python-level ``device_put`` at all (see
    ``_run``) and the program is stable across swaps.

    Every bucket must divide by the ``dp`` axis size — the batch rows
    split evenly across the slice (the default 64/512 rungs divide any
    power-of-two dp width).
    """

    is_sharded = True

    def __init__(
        self,
        policy: Any,
        mesh: Mesh,
        buckets: Tuple[int, ...] = DEFAULT_SHARDED_BUCKETS,
        rules: Sequence[Tuple[str, P]] = DEFAULT_PARTITION_RULES,
        max_traces_per_bucket: Optional[int] = 1,
        seed: int = 0,
        dtype: Optional[str] = None,
    ) -> None:
        if "dp" not in mesh.shape:
            raise ValueError(
                f"sharded serving needs a 'dp' mesh axis for the request "
                f"batch; mesh has {dict(mesh.shape)}"
            )
        dp = mesh.shape["dp"]
        bad = [b for b in buckets if b % dp != 0]
        if bad:
            raise ValueError(
                f"sharded buckets must divide by dp={dp}; {bad} do not "
                "(rows split evenly across the mesh slice)"
            )
        self.mesh = mesh
        self.rules = tuple(rules)
        self.param_specs = match_partition_rules(
            self.rules, policy.params, mesh
        )
        self.param_shardings = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec),
            self.param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self._shard_fns, self._gather_fns = make_shard_and_gather_fns(
            self.param_specs, mesh
        )
        # Requests shard on their leading (batch) axis; trailing feature
        # dims stay local to each device. One partial spec covers every
        # request rank.
        self._batch_sharding = NamedSharding(mesh, P("dp"))
        # Place the wrapped policy's own params once, now — the
        # standalone default for nn_params=None (fleet dispatches pass
        # the registry snapshot, itself placed once at commit).
        self._params_on_mesh = self.shard_params(policy.params)
        # Per-rung AOT executables, built lazily on first dispatch (see
        # _run). The lock serializes the one lowering per rung — a
        # concurrent lower would burn a second trace against the
        # budget-1 guard.
        self._compiled: Dict[int, Any] = {}  # graftlock: guarded-by=_compile_lock
        self._compile_lock = threading.Lock()
        # bucket -> program-ledger dispatch key (set when the rung's
        # AOT executable registers; see _run).
        self._ledger_keys: Dict[int, Optional[str]] = {}  # graftlock: guarded-by=_compile_lock
        self._seed = int(seed)
        super().__init__(
            policy,
            buckets=buckets,
            max_traces_per_bucket=max_traces_per_bucket,
            seed=seed,
            dtype=dtype,
        )

    # -- placement (the once-per-event path) -----------------------------

    def shard_params(self, params: Any) -> Any:
        """Place a host (or anywhere) param tree onto the mesh under the
        partition rules. The ONLY sanctioned placement path — called at
        engine build and reload commit, never per request."""
        return jax.tree_util.tree_map(
            lambda f, leaf: f(leaf), self._shard_fns, params
        )

    def gather_params(self, params: Any) -> Any:
        """Gather a mesh-resident tree back to host arrays."""
        return jax.tree_util.tree_map(
            lambda f, leaf: f(leaf), self._gather_fns, params
        )

    def adopt_params(self, params: Any) -> Any:
        """Replace the engine's resident tree with ``params`` placed
        under the partition rules, and return the placed tree. The
        elastic prewarm path uses this to put the CURRENT fleet params
        on a freshly built slice — replacing the boot copy taken from
        the wrapped policy, so the slice holds exactly one resident
        tree (no double residency against the swap watermark)."""
        self._params_on_mesh = self.shard_params(params)
        return self._params_on_mesh

    # -- compiled path ---------------------------------------------------

    def _build_act(self, bucket: int):
        """Rungs take the DISPATCH COUNTER, not a PRNG key: the per-call
        ``fold_in`` is itself a jit dispatch on the host (~0.27 ms
        measured on this container), so the sharded program derives
        ``fold_in(PRNGKey(seed), counter)`` in-program instead — fused
        into the rung, off the host path. Bitwise identical to the base
        engine's host-side fold (pinned by the parity gate): same seed,
        same counter sequence, same threefry bits."""
        seed = self._seed

        def _act(nn_params, obs, counter, deterministic):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), counter)
            return self._act_core(nn_params, obs, key, deterministic)

        # A distinctive module name so profiles and the program ledger
        # attribute the rung (the AOT path registers explicitly in
        # _run, where the lowered/compiled artifacts are in hand).
        dtype_tag = "bf16" if self.dtype is not None else "f32"
        _act.__name__ = f"sharded_act_rung{bucket}_{dtype_tag}"
        donate = () if jax.default_backend() == "cpu" else (1,)
        return jax.jit(
            self.guards[bucket].wrap(_act), donate_argnums=donate
        )

    def _next_key(self):
        # The counter rides as a strong uint32 scalar (no weak-type
        # retrace); the program folds it into the key (see _build_act).
        with self._lock:
            count = self._dispatches
            self._dispatches += 1
        return np.uint32(count)

    # -- per-dispatch hooks ---------------------------------------------

    def _run(
        self,
        bucket: int,
        nn_params: Any,
        padded: np.ndarray,
        key: jax.Array,
        det: np.bool_,
    ):
        """Dispatch through a per-rung AOT executable.

        The first dispatch of a rung places the padded buffer under the
        ``P("dp")`` batch sharding, lowers the guarded jit against that
        committed layout, and caches ``.compile()``'s executable — the
        one trace the budget-1 RetraceGuard permits. Every later
        dispatch hands the HOST buffer straight to the executable: the
        runtime ingests it under the compiled input layout itself,
        skipping both pjit's python dispatch (arg-sharding resolution
        per call) and a per-request ``jax.device_put`` on the request
        path (measured p50 1.31 ms vs 1.54 ms for the pjit+device_put
        path at the 512 rung on the dp=2 CPU mesh — and rule-16 clean
        by construction). Fresh data still crosses the host boundary
        exactly once; *params* never do (placed at build / reload
        commit only).

        A hot swap keeps the executable: new param trees arrive under
        the same shardings/avals (placed by ``shard_params`` at the
        barrier commit), and an executable call is aval-strict — a
        structure or layout drift raises instead of silently
        recompiling, the same contract the RetraceGuard enforces on the
        pjit path.
        """
        ledger = get_ledger()
        exe = self._compiled.get(bucket)
        if exe is None:
            with self._compile_lock:
                exe = self._compiled.get(bucket)
                if exe is None:
                    placed = jax.device_put(padded, self._batch_sharding)
                    t_lower = time.perf_counter()
                    lowered = self._acts[bucket].lower(
                        nn_params, placed, key, det
                    )
                    t_compile = time.perf_counter()
                    exe = lowered.compile()
                    compile_done = time.perf_counter()
                    self._compiled[bucket] = exe
                    # The richest ledger entry in the repo: the AOT
                    # path holds the compiled jax.stages artifact and
                    # the measured lower/compile walls directly
                    # (obs/ledger.py; never raises into serving).
                    if ledger.enabled:
                        dtype_tag = (
                            "bf16" if self.dtype is not None else "f32"
                        )
                        name = f"act_rung{bucket}_{dtype_tag}_aot"
                        try:
                            self._ledger_keys[bucket] = (
                                register_aot_program(
                                    name=name,
                                    subsystem="serving_sharded",
                                    compiled=exe,
                                    fingerprint=(
                                        f"rung {bucket} x "
                                        f"{padded.shape[-1]} obs, "
                                        f"mesh {self.mesh.shape}"
                                    ),
                                    timings={
                                        "lower_seconds": (
                                            t_compile - t_lower
                                        ),
                                        "compile_seconds": (
                                            compile_done - t_compile
                                        ),
                                    },
                                )
                            )
                        except Exception:  # noqa: BLE001 — observe only
                            pass
                    t0 = time.perf_counter()
                    out = exe(nn_params, placed, key, det)
                    self._ledger_dispatch(
                        ledger, bucket, time.perf_counter() - t0
                    )
                    return out
        if not ledger.enabled:
            return exe(nn_params, padded, key, det)
        t0 = time.perf_counter()
        out = exe(nn_params, padded, key, det)
        self._ledger_dispatch(ledger, bucket, time.perf_counter() - t0)
        return out

    def _ledger_dispatch(
        self, ledger: Any, bucket: int, seconds: float
    ) -> None:
        key = self._ledger_keys.get(bucket)
        if key is not None:
            ledger.dispatch(key, seconds)

    def _default_params(self) -> Any:
        return self._params_on_mesh
