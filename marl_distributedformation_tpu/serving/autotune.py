"""Earn the ladder: pick bucket rungs + coalescing window from observed
traffic instead of guessing.

The serving ladder (1/8/64/512) and the 2 ms coalescing window were
hand-picked in PR 2 and never revisited — the classic way a serving
config rots. This module makes both *earned*: feed it the request-size
distribution and arrival rate of a :class:`~.loadgen.RequestTrace`
(synthetic or recorded) and it returns a :class:`LadderPlan`:

- **Rungs** by exact dynamic programming over the observed sizes:
  choose at most ``max_rungs`` bucket values (from the candidate set of
  observed sizes, rounded up to any mesh-divisibility constraint)
  minimizing total padded capacity — the direct cost model of the
  bucket ladder, where serving a size-``s`` request on rung ``b >= s``
  costs ``b`` rows of compute. The DP is exact and deterministic: the
  same trace always yields the same ladder (pinned by test — an
  autotuner that flaps on identical input would churn compiled rungs).
- **Coalescing window** from the arrival process: the window exists to
  fill batches, so it should be about the time a target batch takes to
  *arrive* at the observed rate — capped at a fraction of the p95
  budget (a window the size of the SLO would spend the whole budget
  waiting) and floored at zero.
- **Sharded split**: rungs at or above ``sharded_min_rows`` (when a
  mesh slice is available) are the sharded engine's ladder, the rest
  stay on the replicated single-device engines — the router's routing
  threshold falls out of the same plan.

The DP is pure — one trace in, one plan out — so the SAME plan shape
serves two callers: offline (the bench harness builds a fleet from a
plan before traffic) and live (serving/elastic replays the recent
recorded window through :func:`replay_recorder` and lands the new plan
at the fleet batch barrier after prewarming every rung off the serving
path). A rung change still means new compiles — the elastic controller
pays them at prewarm, where the budget-1 RetraceGuards receipt them
deliberately, never on the request path.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from marl_distributedformation_tpu.serving.loadgen import RequestTrace


@dataclasses.dataclass(frozen=True)
class LadderPlan:
    """An earned serving configuration, derived from one trace."""

    buckets: Tuple[int, ...]
    window_ms: float
    expected_occupancy_pct: float  # rows / padded capacity over the trace
    baseline_occupancy_pct: float  # same, on the ladder it replaces
    sharded_buckets: Tuple[int, ...]  # rungs the mesh slice should own
    replicated_buckets: Tuple[int, ...]
    observed_rps: float
    mean_rows_per_request: float
    # The dedicated lane's own coalescing window. 0.0 when every request
    # the router sends there already fills its smallest rung (the
    # min_rows floor >= the rung): the window exists to FILL batches
    # from mixed small arrivals, so a lane of pre-filled rungs waiting
    # is pure added latency.
    sharded_window_ms: float = 0.0

    def to_dict(self) -> Dict[str, object]:
        return {
            "buckets": list(self.buckets),
            "window_ms": round(self.window_ms, 3),
            "sharded_window_ms": round(self.sharded_window_ms, 3),
            "expected_occupancy_pct": round(
                self.expected_occupancy_pct, 2
            ),
            "baseline_occupancy_pct": round(
                self.baseline_occupancy_pct, 2
            ),
            "sharded_buckets": list(self.sharded_buckets),
            "replicated_buckets": list(self.replicated_buckets),
            "observed_rps": round(self.observed_rps, 2),
            "mean_rows_per_request": round(
                self.mean_rows_per_request, 3
            ),
        }


def _round_up(value: int, multiple: int) -> int:
    return ((value + multiple - 1) // multiple) * multiple


def padded_cost(sizes: np.ndarray, buckets: Sequence[int]) -> int:
    """Total padded rows a ladder spends serving ``sizes`` — the DP's
    objective, reusable as an evaluation metric for any ladder. Sizes
    above the top rung split into top-rung chunks plus a bucketed
    remainder, mirroring ``BucketedPolicyEngine.plan``."""
    ladder = sorted(set(int(b) for b in buckets))
    top = ladder[-1]
    total = 0
    for s in np.asarray(sizes, np.int64):
        s = int(s)
        total += (s // top) * top
        rest = s % top
        if rest:
            total += next(b for b in ladder if rest <= b)
    return total


def choose_buckets(
    sizes: np.ndarray,
    max_rungs: int = 4,
    divisor: int = 1,
    min_top: Optional[int] = None,
) -> Tuple[int, ...]:
    """Exact minimal-padded-cost ladder of at most ``max_rungs`` rungs.

    Candidates are the observed sizes rounded up to ``divisor``
    multiples (a sharded rung must divide by the mesh's dp width);
    ``min_top`` forces the top rung to at least that value (so a trace
    with no giant requests still keeps headroom for one). Exact DP:
    ``cost[j][k]`` = minimal padded rows covering the smallest ``j``
    candidate sizes with ``k`` rungs, the k-th being candidate ``j``.
    Deterministic — ties resolve to the first (smallest) candidate.
    """
    sizes = np.asarray(sizes, np.int64)
    if sizes.size == 0:
        raise ValueError("cannot tune a ladder from an empty trace")
    if max_rungs < 1:
        raise ValueError(f"need at least one rung, got {max_rungs}")
    divisor = max(1, int(divisor))
    rounded = np.array(
        [_round_up(int(s), divisor) for s in sizes], np.int64
    )
    cands, counts = np.unique(rounded, return_counts=True)
    if min_top is not None and cands[-1] < min_top:
        top = _round_up(int(min_top), divisor)
        cands = np.append(cands, top)
        counts = np.append(counts, 0)
    m = len(cands)
    k_max = min(max_rungs, m)
    # weight[i] = requests whose rounded size is cands[i]; covering
    # cands[(i..j]] with rung cands[j] costs cands[j] * sum(weights).
    prefix = np.concatenate([[0], np.cumsum(counts)])
    INF = float("inf")
    cost = [[INF] * (k_max + 1) for _ in range(m)]
    parent: List[List[Optional[int]]] = [
        [None] * (k_max + 1) for _ in range(m)
    ]
    for j in range(m):
        cost[j][1] = int(cands[j]) * int(prefix[j + 1])
    for k in range(2, k_max + 1):
        for j in range(k - 1, m):
            for i in range(k - 2, j):
                c = cost[i][k - 1] + int(cands[j]) * int(
                    prefix[j + 1] - prefix[i + 1]
                )
                if c < cost[j][k]:
                    cost[j][k] = c
                    parent[j][k] = i
    best_k = min(
        range(1, k_max + 1), key=lambda k: (cost[m - 1][k], k)
    )
    rungs: List[int] = []
    j: Optional[int] = m - 1
    k = best_k
    while j is not None and k >= 1:
        rungs.append(int(cands[j]))
        j = parent[j][k]
        k -= 1
    return tuple(sorted(rungs))


def choose_window_ms(
    rate_rps: float,
    mean_rows_per_request: float,
    fill_rows: int,
    p95_target_ms: float,
    max_fraction_of_slo: float = 0.2,
) -> float:
    """Coalescing window: time for ``fill_rows`` rows to ARRIVE at the
    observed rate, capped at ``max_fraction_of_slo`` of the p95 budget.
    At high rates the window collapses toward zero (batches fill from
    backlog alone); at low rates the cap keeps latency honest — an
    empty server must not hold a lone request hostage to fill a rung."""
    if rate_rps <= 0 or mean_rows_per_request <= 0:
        return max_fraction_of_slo * p95_target_ms
    t_fill_ms = 1e3 * fill_rows / (rate_rps * mean_rows_per_request)
    return max(0.0, min(t_fill_ms, max_fraction_of_slo * p95_target_ms))


def autotune_ladder(
    trace: RequestTrace,
    p95_target_ms: float,
    max_rungs: int = 4,
    mesh_divisor: int = 1,
    sharded_min_rows: Optional[int] = None,
    baseline_buckets: Sequence[int] = (1, 8, 64, 512),
    fill_fraction: float = 0.5,
) -> LadderPlan:
    """One trace in, one :class:`LadderPlan` out (module docstring).

    ``mesh_divisor`` is the dp width rungs above ``sharded_min_rows``
    must divide (the sharded engine's constraint); ``fill_fraction``
    sizes the coalescing target as a share of the smallest big rung (a
    window that reliably half-fills the rung it feeds is already deep
    into the batching win, without waiting for the perfect batch)."""
    sizes = np.asarray(trace.sizes, np.int64)
    split_at = (
        sharded_min_rows
        if sharded_min_rows is not None
        else max(int(sizes.max()) // 8, int(np.median(sizes)) + 1)
    )
    # Small rungs are unconstrained; rungs at/above the sharded split
    # must divide the mesh. Tune them jointly (one cost model), then
    # split the ladder for the two engine kinds.
    small = sizes[sizes < split_at]
    big = sizes[sizes >= split_at]
    rungs: List[int] = []
    if small.size:
        small_rungs = max(1, max_rungs - (1 if big.size else 0))
        rungs.extend(
            choose_buckets(small, max_rungs=small_rungs, divisor=1)
        )
    if big.size:
        big_rungs = max(1, max_rungs - len(rungs))
        rungs.extend(
            choose_buckets(
                big, max_rungs=big_rungs, divisor=max(1, mesh_divisor)
            )
        )
    buckets = tuple(sorted(set(rungs)))
    sharded = tuple(b for b in buckets if big.size and b >= split_at)
    replicated = tuple(b for b in buckets if b not in sharded)
    total_rows = int(sizes.sum())
    tuned_cost = padded_cost(sizes, buckets)
    base_cost = padded_cost(sizes, baseline_buckets)
    mean_rows = float(sizes.mean())
    fill_rows = max(
        1, int(fill_fraction * (min(sharded) if sharded else max(buckets)))
    )
    window_ms = choose_window_ms(
        trace.offered_rps, mean_rows, fill_rows, p95_target_ms
    )
    # Routing floor = the sharded split point; when it fills the slice's
    # smallest rung on arrival, the lane has nothing to coalesce. Only a
    # floor BELOW the rung (partial-rung requests pad up) re-earns the
    # global window.
    sharded_window_ms = (
        window_ms if sharded and split_at < min(sharded) else 0.0
    )
    return LadderPlan(
        buckets=buckets,
        window_ms=window_ms,
        expected_occupancy_pct=(
            100.0 * total_rows / tuned_cost if tuned_cost else 0.0
        ),
        baseline_occupancy_pct=(
            100.0 * total_rows / base_cost if base_cost else 0.0
        ),
        sharded_buckets=sharded,
        replicated_buckets=replicated,
        observed_rps=trace.offered_rps,
        mean_rows_per_request=mean_rows,
        sharded_window_ms=sharded_window_ms,
    )


def replay_recorder(
    recorder: "object",
    p95_target_ms: float,
    min_requests: int = 64,
    **autotune_kwargs: object,
) -> Optional[LadderPlan]:
    """The incremental live entrypoint: replay a
    :class:`~.loadgen.TraceRecorder`'s recent window through the exact
    same DP. Returns None below ``min_requests`` recorded arrivals — a
    ladder re-derived from a handful of requests would flap, and every
    flap costs prewarm compiles."""
    if len(recorder) < max(2, int(min_requests)):  # type: ignore[arg-type]
        return None
    trace = recorder.to_trace()  # type: ignore[attr-defined]
    if trace is None:
        return None
    return autotune_ladder(trace, p95_target_ms, **autotune_kwargs)


def plans_equivalent(
    a: Optional[LadderPlan],
    b: Optional[LadderPlan],
    window_tol_ms: float = 1.0,
) -> bool:
    """Hysteresis predicate: two plans that would build the same
    engines (same rung ladders, same sharded split, windows within
    ``window_tol_ms``) are the same capacity decision — re-splitting
    between them would pay prewarm compiles and a barrier pause to
    change nothing."""
    if a is None or b is None:
        return a is b
    return (
        a.replicated_buckets == b.replicated_buckets
        and a.sharded_buckets == b.sharded_buckets
        and abs(a.window_ms - b.window_ms) <= window_tol_ms
        and abs(a.sharded_window_ms - b.sharded_window_ms)
        <= window_tol_ms
    )
