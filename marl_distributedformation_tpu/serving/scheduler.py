"""Micro-batching request scheduler: the host-side half of serving.

One worker thread owns the accelerator. Clients enqueue requests into a
bounded queue; the worker takes the first request, then keeps absorbing
arrivals until the coalescing deadline (``window_ms``) passes or the top
bucket is full, and dispatches the coalesced rows through the engine as
ONE padded batch. Per-request results are sliced back out and resolved
on each caller's future.

The three failure-shaped paths are explicit:

- **Backpressure** — a full queue rejects immediately with
  :class:`BackpressureError` carrying ``retry_after_s`` (priced from the
  current depth times the recent mean batch time). Rejecting at the door
  beats queueing unboundedly: the caller knows *now* and the p99 of
  accepted requests stays bounded.
- **Per-request timeouts** — a request whose deadline passed while it
  waited is failed with :class:`RequestTimeout` at dispatch time (never
  silently computed for a caller that already gave up).
- **Dispatch errors** — an engine exception fails that batch's futures
  and the worker keeps serving; a serving process never dies with
  requests in flight.

Model hot-swap composes here: the worker snapshots ``(params, step)``
from the registry once per micro-batch, so a swap lands atomically
between batches and every result records the checkpoint step that
produced it (``ServedResult.model_step``).

**SLO classes.** Every request carries an admission class —
``"interactive"`` (the default: a user is waiting) or ``"batch"``
(eval sweeps, backfills: work that tolerates deferral). Under
backpressure batch traffic YIELDS: (1) dispatch order prefers queued
interactive requests, so batch backlog cannot stretch the interactive
p95; (2) a full queue never rejects an interactive request while batch
requests are queued — the newest-queued batch request is *preempted*
(its future fails with ``BackpressureError`` + retry-after, the same
contract as a door reject, which the client retry loop already honors)
and the interactive request takes its slot. With all-default traffic
the queue is plain FIFO — the classes cost nothing until used.

**Tenant lanes.** Constructed with ``registries`` (a ``model_id`` →
registry mapping — serving/tenancy builds it), the scheduler multiplexes
NAMED MODEL LANES over the one engine: every request carries a
``model_id``, admission is a separate bounded two-class queue PER LANE
(one tenant's batch storm fills only its own lane — others admit
untouched, and preemption never crosses a lane), backpressure is priced
per lane, dispatch drains lanes round-robin with interactive-anywhere
ahead of batch-anywhere, and each dispatch group snapshots ITS lane's
``(params, step)`` and runs under ITS lane's batch barrier — so a
reload coordinator committing one lane quiesces only that lane's
groups while every other lane keeps dispatching. The params ride
``engine.act(nn_params=...)`` as traced inputs, so same-architecture
lanes share the engine's compiled rung executables.
"""

from __future__ import annotations

import contextlib
import dataclasses
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, List, Optional

import numpy as np

from marl_distributedformation_tpu.chaos.plane import fault_point
from marl_distributedformation_tpu.obs import get_tracer
from marl_distributedformation_tpu.serving.engine import BucketedPolicyEngine
from marl_distributedformation_tpu.serving.metrics import ServingMetrics


class BackpressureError(RuntimeError):
    """Queue full: retry after ``retry_after_s`` (reject-with-retry-after)."""

    def __init__(self, retry_after_s: float) -> None:
        super().__init__(
            f"serving queue full; retry after {retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s


class RequestTimeout(TimeoutError):
    """The request's deadline passed while it waited in the queue."""


class SchedulerStopped(RuntimeError):
    """The scheduler shut down before this request was dispatched."""


SLO_INTERACTIVE = "interactive"
SLO_BATCH = "batch"
SLO_CLASSES = (SLO_INTERACTIVE, SLO_BATCH)


@dataclasses.dataclass
class ServedResult:
    """What a resolved request future carries."""

    actions: np.ndarray
    model_step: int  # checkpoint step of the params that answered
    latency_s: float  # enqueue -> result
    replica: int = -1  # fleet replica index (-1: single-engine serving)
    model_id: Optional[str] = None  # tenant lane (None: single-model)


@dataclasses.dataclass
class _Request:
    obs: np.ndarray
    deterministic: bool
    future: Future
    enqueued: float
    timeout_s: Optional[float]
    trace_id: Optional[str] = None
    slo_class: str = SLO_INTERACTIVE
    model_id: Optional[str] = None

    def expired(self, now: float) -> bool:
        return self.timeout_s is not None and (
            now - self.enqueued > self.timeout_s
        )


class _ClassedQueue:
    """Bounded two-class request queue: interactive ahead of batch.

    The ``queue.Queue`` subset the scheduler uses (``put_nowait`` /
    ``get`` / ``get_nowait`` / ``qsize``, ``queue.Full``/``Empty``
    semantics), with the SLO-class admission policy inside:

    - ``get`` pops the oldest INTERACTIVE request first; batch requests
      dispatch only when no interactive request is queued (each class
      stays FIFO within itself).
    - ``put_nowait`` on a full queue returns the preempted batch
      request when the arrival is interactive and batch work is queued
      (newest batch yields — it has waited least), instead of raising
      ``queue.Full``. The caller owns failing the preempted future.

    A plain lock+deques structure instead of queue.Queue: preemption
    needs to remove from the middle of the bound, which Queue cannot.
    """

    def __init__(self, maxsize: int) -> None:
        self._maxsize = maxsize
        self._cond = threading.Condition()
        self._interactive: "deque[_Request]" = deque()  # graftlock: guarded-by=_cond
        self._batch: "deque[_Request]" = deque()  # graftlock: guarded-by=_cond

    def qsize(self) -> int:
        with self._cond:
            return len(self._interactive) + len(self._batch)

    def put_nowait(self, req: _Request) -> Optional[_Request]:
        """Admit ``req``; returns a preempted batch request (fail its
        future) or None. Raises ``queue.Full`` when admission fails."""
        with self._cond:
            depth = len(self._interactive) + len(self._batch)
            lane = (
                self._batch
                if req.slo_class == SLO_BATCH
                else self._interactive
            )
            if depth < self._maxsize:
                lane.append(req)
                self._cond.notify()
                return None
            if req.slo_class != SLO_BATCH and self._batch:
                evicted = self._batch.pop()
                self._interactive.append(req)
                self._cond.notify()
                return evicted
            raise queue.Full

    # graftlock: holds=_cond
    def _pop(self) -> Optional[_Request]:
        if self._interactive:
            return self._interactive.popleft()
        if self._batch:
            return self._batch.popleft()
        return None

    def get(self, timeout: Optional[float] = None) -> _Request:
        deadline = (
            None if timeout is None else time.perf_counter() + timeout
        )
        with self._cond:
            while True:
                req = self._pop()
                if req is not None:
                    return req
                if deadline is None:
                    self._cond.wait()
                    continue
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise queue.Empty
                self._cond.wait(remaining)

    def get_nowait(self) -> _Request:
        with self._cond:
            req = self._pop()
            if req is None:
                raise queue.Empty
            return req


class _TenantAdmission:
    """Per-tenant bounded admission: one two-class queue per model lane.

    The same ``put_nowait`` / ``get`` / ``get_nowait`` / ``qsize``
    surface as :class:`_ClassedQueue`, with the isolation contract
    inside:

    - **Bounds are per lane.** A tenant filling its own ``maxsize``
      admission budget gets ``queue.Full`` (→ per-tenant backpressure);
      every other lane's budget is untouched — a 512-rung batch storm on
      one lane cannot consume another lane's slots.
    - **Preemption stays within a lane.** A full lane's interactive
      arrival preempts the newest BATCH request of the SAME lane only;
      another tenant's batch work is never evicted for this tenant's
      interactive traffic.
    - **Draining is round-robin across lanes**, interactive-anywhere
      ahead of batch-anywhere: lane B's interactive request dispatches
      before lane A's batch backlog no matter how deep A's queue is,
      and equal-class lanes take turns instead of starving on arrival
      order.
    """

    def __init__(self, lanes: Any, maxsize: int) -> None:
        self._maxsize = maxsize  # per-lane admission bound
        self._cond = threading.Condition()
        # lane -> (interactive deque, batch deque), draining order fixed
        # at construction (the directory's lane order).
        self._lanes = {  # graftlock: guarded-by=_cond
            mid: (deque(), deque()) for mid in lanes
        }
        self._order = list(self._lanes)
        self._rr = 0  # graftlock: guarded-by=_cond

    def qsize(self) -> int:
        with self._cond:
            return sum(
                len(i) + len(b) for i, b in self._lanes.values()
            )

    def lane_depth(self, model_id: str) -> int:
        with self._cond:
            i, b = self._lanes[model_id]
            return len(i) + len(b)

    def put_nowait(self, req: _Request) -> Optional[_Request]:
        """Admit ``req`` into its lane; returns a preempted same-lane
        batch request (fail its future) or None. ``queue.Full`` when the
        LANE's budget is exhausted — per-tenant backpressure."""
        with self._cond:
            interactive, batch = self._lanes[req.model_id]
            depth = len(interactive) + len(batch)
            lane = batch if req.slo_class == SLO_BATCH else interactive
            if depth < self._maxsize:
                lane.append(req)
                self._cond.notify()
                return None
            if req.slo_class != SLO_BATCH and batch:
                evicted = batch.pop()
                interactive.append(req)
                self._cond.notify()
                return evicted
            raise queue.Full

    # graftlock: holds=_cond
    def _pop(self) -> Optional[_Request]:
        n = len(self._order)
        for cls_idx in (0, 1):  # 0: interactive pass, 1: batch pass
            for k in range(n):
                mid = self._order[(self._rr + k) % n]
                dq = self._lanes[mid][cls_idx]
                if dq:
                    self._rr = (self._rr + k + 1) % n
                    return dq.popleft()
        return None

    def get(self, timeout: Optional[float] = None) -> _Request:
        deadline = (
            None if timeout is None else time.perf_counter() + timeout
        )
        with self._cond:
            while True:
                req = self._pop()
                if req is not None:
                    return req
                if deadline is None:
                    self._cond.wait()
                    continue
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise queue.Empty
                self._cond.wait(remaining)

    def get_nowait(self) -> _Request:
        with self._cond:
            req = self._pop()
            if req is None:
                raise queue.Empty
            return req


class MicroBatchScheduler:
    """Deadline-window micro-batching over a :class:`BucketedPolicyEngine`.

    Args:
      engine: the compiled act functions.
      registry: optional ``ModelRegistry``; ``None`` serves the engine's
        wrapped policy params forever (step reported as 0).
      max_queue: bound on queued *requests*; the backpressure knob.
      window_ms: coalescing deadline. 0 disables coalescing (each request
        dispatches alone — the latency-over-throughput corner).
      default_timeout_s: per-request deadline when ``submit`` gets none.
      logger: optional ``utils.logging.MetricsLogger``; a metrics record
        is emitted every ``emit_every`` batches.
      registries: optional ``model_id`` → registry mapping — turns the
        scheduler multi-tenant (module docstring "Tenant lanes"): every
        ``submit`` must then carry a known ``model_id``, admission is a
        per-lane bounded queue, and each dispatch group runs under its
        lane's batch barrier with its lane's params. Mutually exclusive
        with ``registry``.
      tenant_max_queue: per-lane admission bound in tenant mode
        (default: ``max_queue``, applied per lane).
    """

    def __init__(
        self,
        engine: BucketedPolicyEngine,
        registry: Any = None,
        max_queue: int = 256,
        window_ms: float = 2.0,
        default_timeout_s: float = 10.0,
        metrics: Optional[ServingMetrics] = None,
        logger: Any = None,
        emit_every: int = 100,
        registries: Any = None,
        tenant_max_queue: Optional[int] = None,
        trace_recorder: Any = None,
    ) -> None:
        if registries is not None and registry is not None:
            raise ValueError(
                "pass either registry (single-model) or registries "
                "(tenant lanes), not both"
            )
        self.engine = engine
        self.registry = registry
        self.registries = registries
        self.window_s = window_ms / 1e3
        self.default_timeout_s = default_timeout_s
        self.metrics = metrics or ServingMetrics()
        self.logger = logger
        self.emit_every = emit_every
        if registries is not None:
            if not registries:
                raise ValueError("registries must declare at least one lane")
            self._queue: Any = _TenantAdmission(
                registries, maxsize=tenant_max_queue or max_queue
            )
        else:
            self._queue = _ClassedQueue(maxsize=max_queue)
        # Optional loadgen.TraceRecorder: OFFERED arrivals (rows + SLO
        # class) recorded at submit, before admission control — the
        # live-trace feed for the elastic retuner and --record-trace.
        self.trace_recorder = trace_recorder
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._busy = False  # worker mid-dispatch (drain estimation)

    # -- client side -----------------------------------------------------

    def submit(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
        trace_id: Optional[str] = None,
        slo_class: str = SLO_INTERACTIVE,
        model_id: Optional[str] = None,
    ) -> Future:
        """Enqueue one request of ``(n, *row_shape)`` observation rows.
        Returns a future resolving to :class:`ServedResult`. Raises
        :class:`BackpressureError` when the queue is full. ``trace_id``
        rides the request to the dispatch batch span (obs/) so one ID
        correlates a request across frontend, router, and batch.
        ``slo_class`` is the admission class (module docstring): batch
        requests yield to interactive ones under backpressure.
        ``model_id`` names the tenant lane — required (and validated
        against the declared lanes) in tenant mode, rejected in
        single-model mode."""
        if self._thread is None:
            raise RuntimeError("scheduler not started (use start() / with)")
        if slo_class not in SLO_CLASSES:
            raise ValueError(
                f"unknown slo_class {slo_class!r}; known: {SLO_CLASSES}"
            )
        if self.registries is not None:
            if model_id is None:
                raise ValueError(
                    "this scheduler serves tenant lanes: submit requires "
                    f"model_id (known: {sorted(self.registries)})"
                )
            if model_id not in self.registries:
                raise ValueError(
                    f"unknown model_id {model_id!r}; known lanes: "
                    f"{sorted(self.registries)}"
                )
        elif model_id is not None:
            raise ValueError(
                "this scheduler serves a single model; model_id "
                f"{model_id!r} names a lane it does not have"
            )
        obs = np.asarray(obs, np.float32)
        if obs.ndim < 2 or obs.shape[0] < 1:
            raise ValueError(
                f"obs must be (n >= 1, *row_shape), got shape {obs.shape}"
            )
        if self.trace_recorder is not None:
            # Before admission control: the retuner must see the
            # backpressured arrivals too, or it never sees overload.
            self.trace_recorder.record(int(obs.shape[0]), slo_class)
        req = _Request(
            obs=obs,
            deterministic=bool(deterministic),
            future=Future(),
            enqueued=time.perf_counter(),
            timeout_s=(
                self.default_timeout_s if timeout_s is None else timeout_s
            ),
            trace_id=trace_id,
            slo_class=slo_class,
            model_id=model_id,
        )
        try:
            preempted = self._queue.put_nowait(req)
        except queue.Full:
            self.metrics.record_reject()
            raise BackpressureError(self.retry_after_s(model_id)) from None
        if preempted is not None:
            # A queued batch request yielded its slot to this
            # interactive arrival: same reject-with-retry-after
            # contract as a door reject — the client's existing retry
            # loop re-submits it once pressure eases. In tenant mode
            # the preempted request is by construction the SAME lane's.
            self.metrics.record_preempted()
            if not preempted.future.done():
                preempted.future.set_exception(
                    BackpressureError(self.retry_after_s(model_id))
                )
        if self._stop.is_set():
            # stop() may have drained the queue between our liveness
            # check and the put — there is no worker left to take this
            # request, so drain again ourselves (resolving the future,
            # whether ours or another racing submitter's).
            self._drain_stopped_queue()
        self.metrics.record_submit(self._queue.qsize())
        return req.future

    def retry_after_s(self, model_id: Optional[str] = None) -> float:
        """Backoff hint: the window plus roughly how long the current
        backlog takes to drain at the recent batch rate. With a
        ``model_id`` (tenant mode) the backlog is THAT lane's — one
        lane's storm prices its own retries, not its neighbors'."""
        return self.window_s + self.estimated_drain_s(model_id)

    def estimated_drain_s(self, model_id: Optional[str] = None) -> float:
        """Roughly how long the current backlog takes to drain at the
        recent batch rate — the number a fleet router routes on. The
        in-flight batch counts: a worker stuck in a slow dispatch with
        an empty queue is NOT an idle replica."""
        if model_id is not None and self.registries is not None:
            depth = self._queue.lane_depth(model_id)
        else:
            depth = self._queue.qsize()
        backlog = depth + (1 if self._busy else 0)
        return backlog * self.metrics.mean_batch_seconds()

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    def lane_queue_depth(self, model_id: str) -> int:
        """Queued requests in one tenant lane (tenant mode only)."""
        if self.registries is None:
            raise ValueError("single-model scheduler has no tenant lanes")
        return self._queue.lane_depth(model_id)

    @property
    def alive(self) -> bool:
        """True while the worker thread is serving. A stopped (or
        crashed-at-interpreter-teardown) worker makes every queued future
        dead weight — the router's liveness probe checks this."""
        return self._thread is not None and self._thread.is_alive()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "MicroBatchScheduler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="microbatch-scheduler", daemon=True
        )
        self._thread.start()
        return self

    def restart(self) -> None:
        """Replace a DEAD worker thread (the watchdog's fleet lane): a
        crashed worker leaves ``_thread`` set but not alive — clear it
        and spawn a fresh one. No-op while the worker is alive (a live
        worker owns its queue) and after an explicit ``stop()`` (a
        stopped scheduler stays stopped)."""
        if self._stop.is_set():
            return
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = None
        self.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=30.0)
        self._thread = None
        # Fail anything still queued — no silent dropped futures.
        self._drain_stopped_queue()

    def fail_queued(self) -> None:
        """Fail every queued future with :class:`SchedulerStopped` — the
        router's DEAD-WORKER cleanup. A worker that crashed (rather than
        being stopped) leaves its queue orphaned; without this drain
        those callers wedge forever, with it their futures fail over to
        surviving replicas like any replica fault. Only call when the
        worker is not alive (a live worker owns its queue)."""
        self._drain_stopped_queue()

    def _drain_stopped_queue(self) -> None:
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                req.future.set_exception(
                    SchedulerStopped("scheduler stopped before dispatch")
                )

    def __enter__(self) -> "MicroBatchScheduler":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- worker side -----------------------------------------------------

    def _run(self) -> None:
        try:
            self._serve_loop()
        except BaseException as e:
            # The per-batch backstop in _serve_loop contains dispatch
            # errors; anything escaping to here kills the worker thread
            # outright — every queued future wedges until the router's
            # liveness probe notices. Snapshot the ring for the
            # postmortem before dying.
            get_tracer().incident(
                "scheduler_worker_death",
                error=repr(e),
                queue_depth=self._queue.qsize(),
            )
            raise

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            # Chaos seam: a crash here is a WORKER DEATH — it escapes to
            # _run (incident + thread exit) with no request in hand, and
            # the router's circuit breaker + dead-worker queue drain own
            # the recovery. Deliberately outside the per-batch backstop.
            fault_point("scheduler.dispatch")
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [first]
            rows = first.obs.shape[0]
            deadline = time.perf_counter() + self.window_s
            # Coalesce until the window closes or the top bucket is full
            # (more rows than the top bucket would split into a second
            # dispatch anyway — no latency win in waiting further).
            while rows < self.engine.max_bucket:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                batch.append(nxt)
                rows += nxt.obs.shape[0]
            try:
                self._busy = True
                self._dispatch(batch)
            except Exception as e:  # noqa: BLE001 — the worker must survive
                # Backstop: _dispatch_group already contains engine
                # errors, but nothing outside it may kill the worker —
                # a dead worker wedges every future client forever.
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)
            finally:
                self._busy = False

    def _dispatch(self, batch: List[_Request]) -> None:
        now = time.perf_counter()
        live: List[_Request] = []
        expired = 0
        for req in batch:
            if req.expired(now):
                req.future.set_exception(
                    RequestTimeout(
                        f"request waited {now - req.enqueued:.3f}s "
                        f"(timeout {req.timeout_s:.3f}s)"
                    )
                )
                expired += 1
            else:
                live.append(req)
        if expired:
            self.metrics.record_timeout(expired)
        # Group by (model lane, deterministic, row shape):
        # ``deterministic`` is per-batch (one traced scalar), rows of
        # different trailing shapes cannot share a concatenated buffer,
        # and different lanes answer with different params — one client
        # sending odd-shaped observations must never fail another's
        # request, and one tenant's rows must never meet another's
        # weights.
        groups: dict = {}
        for r in live:
            groups.setdefault(
                (r.model_id, r.deterministic, r.obs.shape[1:]), []
            ).append(r)
        if self.registries is not None:
            # Per-lane barriers: each group runs under ITS lane's
            # barrier only, so a coordinator committing one lane's swap
            # waits out that lane's in-flight groups while every other
            # lane's groups keep dispatching — per-model step
            # monotonicity without a fleet-wide pause.
            for (mid, flag, _), group in groups.items():
                with self.registries[mid].batch_lock:
                    self._dispatch_group(group, flag, model_id=mid)
            return
        # Batch barrier: a registry may expose ``batch_lock`` (the fleet
        # replica registry does), held for the whole dispatch. A reload
        # coordinator that acquires EVERY replica's lock before flipping
        # any pointer gets a fleet-wide point in time with zero batches
        # in flight — the foundation of globally step-monotonic swaps.
        lock = getattr(self.registry, "batch_lock", None)
        with lock if lock is not None else contextlib.nullcontext():
            for (_, flag, _), group in groups.items():
                self._dispatch_group(group, flag)

    def _dispatch_group(
        self,
        group: List[_Request],
        flag: bool,
        model_id: Optional[str] = None,
    ) -> None:
        registry = (
            self.registries[model_id]
            if self.registries is not None
            else self.registry
        )
        if registry is not None:
            nn_params, step = registry.active()
        else:
            nn_params, step = None, 0
        sizes = [r.obs.shape[0] for r in group]
        obs = (
            group[0].obs
            if len(group) == 1
            else np.concatenate([r.obs for r in group], axis=0)
        )
        t0 = time.perf_counter()
        try:
            actions = self.engine.act(
                obs, deterministic=flag, nn_params=nn_params
            )
        except Exception as e:  # noqa: BLE001 — fail the batch, not the server
            for req in group:
                req.future.set_exception(e)
            return
        done = time.perf_counter()
        tracer = get_tracer()
        if tracer.enabled:
            # The batch span LINKS the coalesced requests' trace IDs: a
            # request traced at the frontend is findable inside the
            # dispatch that actually served it. One ring append per
            # batch — host-side, after the engine returned.
            tracer.add_span(
                "serve.batch",
                t0,
                done,
                rows=sum(sizes),
                requests=len(group),
                model_step=int(step),
                model_id=model_id,
                trace_ids=[r.trace_id for r in group if r.trace_id],
            )
        latencies = []
        offset = 0
        for req, n in zip(group, sizes):
            latency = done - req.enqueued
            latencies.append(latency)
            req.future.set_result(
                ServedResult(
                    actions=actions[offset : offset + n],
                    model_step=step,
                    latency_s=latency,
                    model_id=model_id,
                )
            )
            offset += n
        total = sum(sizes)
        self.metrics.record_batch(
            rows=total,
            padded_rows=sum(self.engine.plan(total)),
            batch_seconds=done - t0,
            latencies_s=latencies,
            queue_depth=self._queue.qsize(),
        )
        if (
            self.logger is not None
            and self.metrics.batches_total % self.emit_every == 0
        ):
            record = self.metrics.snapshot()
            record["model_step"] = float(step)
            if registry is not None:
                record["model_swap_count"] = float(registry.swap_count)
            self.logger.log(record, step=self.metrics.batches_total)
