"""Multi-tenant serving: named model lanes over one fleet.

``TenantDirectory`` declares the lanes, ``TenantFleet`` serves them —
same-arch lanes share compiled rung executables (params are traced
inputs), every lane gets its own admission queue, its own reload
coordinator, and its own monotonic step. See docs/serving.md
"Multi-tenant lanes".
"""

from marl_distributedformation_tpu.serving.tenancy.directory import (
    TenantDirectory,
    TenantSpec,
)
from marl_distributedformation_tpu.serving.tenancy.fleet import (
    TenantFleet,
    tenant_fleet_from_directory,
)
from marl_distributedformation_tpu.serving.tenancy.smoke import (
    run_tenant_smoke,
)

__all__ = [
    "TenantDirectory",
    "TenantSpec",
    "TenantFleet",
    "tenant_fleet_from_directory",
    "run_tenant_smoke",
]
