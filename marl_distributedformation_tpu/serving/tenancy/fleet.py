"""TenantFleet: N named model lanes served by ONE fleet.

The multi-tenant serving plane, assembled from the lane-aware
primitives underneath it (nothing here touches a compiled program):

- The :class:`~.directory.TenantDirectory` is grouped by arch signature.
  Each group gets ONE :class:`~..fleet.router.FleetRouter` in lanes
  mode: one ``BucketedPolicyEngine`` per replica serves EVERY lane in
  the group, because params are traced inputs — adding a same-arch
  tenant costs zero compiles (the PR-13 ledger census stays at <= 1
  compile per (arch, rung)). A lane with a DIFFERENT architecture
  (pursuit_evasion next to two formation lanes) lands in its own group
  with its own engines and its own budget-1 receipts.
- Every lane with a ``promoted/`` directory gets its own lane-keyed
  :class:`~..fleet.reload.FleetReloadCoordinator`: N independent
  always-learning pipelines promote into one fleet, and a commit
  acquires only ITS lane's batch barriers — swapping lane A never
  pauses lane B's dispatch groups, while lane A's own step stays
  monotonic in response completion order (per-model monotonicity).
- Admission is per-lane all the way down (scheduler
  ``_TenantAdmission``): lane A's batch storm fills lane A's queue and
  quotes lane A's Retry-After; lane B stays interactive.

The fleet duck-types the router surface ``FleetFrontend`` speaks
(``submit`` / ``snapshot`` / ``lane_ids`` / ``lane_steps`` /
``healthy_replicas`` / ``replicas`` / ``default_timeout_s``), so the
HTTP layer serves multi-tenant without knowing it.
"""

from __future__ import annotations

import threading
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from marl_distributedformation_tpu.serving.engine import DEFAULT_BUCKETS
from marl_distributedformation_tpu.serving.fleet.reload import (
    FleetReloadCoordinator,
)
from marl_distributedformation_tpu.serving.fleet.router import FleetRouter
from marl_distributedformation_tpu.serving.fleet.smoke import warmup_fleet
from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
)
from marl_distributedformation_tpu.serving.tenancy.directory import (
    TenantDirectory,
    TenantSpec,
)


def _tree_signature(params: Any) -> Any:
    """Hashable (structure, shapes, dtypes) fingerprint of a param tree —
    what must match for two lanes to ride one engine's compiled rungs."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    return treedef, tuple(
        (getattr(x, "shape", ()), str(getattr(x, "dtype", type(x))))
        for x in leaves
    )


class TenantFleet:
    """Named model lanes over shared per-arch fleet routers.

    Args:
      directory: the declared lanes (``TenantDirectory``).
      policies: ``model_id`` → ``LoadedPolicy`` seeding each lane.
        Every declared lane needs exactly one. Within an arch group,
        every lane's param tree must match the group representative's
        (structure + leaf shapes/dtypes) — checked here, fail-fast,
        because a mismatched tree would otherwise surface as a shape
        crash inside a compiled rung at first dispatch.
      steps: optional ``model_id`` → initial checkpoint step (default 0;
        ``tenant_fleet_from_directory`` passes each lane's real step).
      devices / num_replicas / buckets / window_ms / max_queue /
      default_timeout_s / seed / max_failovers / probe_interval_s:
        forwarded to every arch group's ``FleetRouter``.
      tenant_max_queue: per-lane admission bound (default ``max_queue``).
      poll_interval_s / commit_timeout_s: forwarded to every lane's
        reload coordinator.
      watch: when True, ``start()`` also starts each lane coordinator's
        background watcher (tests drive ``refresh()`` by hand instead).
    """

    def __init__(
        self,
        directory: TenantDirectory,
        policies: Mapping[str, Any],
        steps: Optional[Mapping[str, int]] = None,
        devices: Optional[Sequence[Any]] = None,
        num_replicas: Optional[int] = None,
        buckets: Tuple[int, ...] = DEFAULT_BUCKETS,
        window_ms: float = 2.0,
        max_queue: int = 256,
        tenant_max_queue: Optional[int] = None,
        default_timeout_s: float = 10.0,
        seed: int = 0,
        max_failovers: int = 1,
        probe_interval_s: float = 1.0,
        poll_interval_s: float = 2.0,
        commit_timeout_s: float = 30.0,
        watch: bool = False,
    ) -> None:
        if len(directory) == 0:
            raise ValueError("TenantFleet needs at least one declared lane")
        missing = [mid for mid in directory if mid not in policies]
        if missing:
            raise ValueError(
                f"no seed policy for declared lanes: {missing}"
            )
        extra = [mid for mid in policies if mid not in directory]
        if extra:
            raise ValueError(
                f"policies for undeclared lanes: {extra} "
                f"(declared: {sorted(directory)})"
            )
        self.directory = directory
        self.default_timeout_s = default_timeout_s
        self.lane_ids: Tuple[str, ...] = tuple(directory)
        self.watch = watch
        steps = dict(steps or {})
        # One router per arch group; lanes in a group share its engines.
        self.routers: Dict[str, FleetRouter] = {}
        self._router_for: Dict[str, FleetRouter] = {}
        for arch, specs in directory.arch_groups().items():
            rep = policies[specs[0].model_id]
            rep_sig = _tree_signature(rep.params)
            for spec in specs[1:]:
                sig = _tree_signature(policies[spec.model_id].params)
                if sig != rep_sig:
                    raise ValueError(
                        f"lane {spec.model_id!r} declares arch {arch} "
                        f"(same as {specs[0].model_id!r}) but its param "
                        "tree differs in structure/shape/dtype — it "
                        "cannot share the group's compiled rungs"
                    )
            lanes = {
                spec.model_id: (
                    policies[spec.model_id].params,
                    int(steps.get(spec.model_id, 0)),
                )
                for spec in specs
            }
            router = FleetRouter(
                rep,
                devices=devices,
                num_replicas=num_replicas,
                buckets=buckets,
                window_ms=window_ms,
                max_queue=max_queue,
                tenant_max_queue=tenant_max_queue,
                default_timeout_s=default_timeout_s,
                seed=seed,
                max_failovers=max_failovers,
                probe_interval_s=probe_interval_s,
                lanes=lanes,
            )
            self.routers[arch] = router
            for spec in specs:
                self._router_for[spec.model_id] = router
        # One lane-keyed coordinator per promoting lane: its commit
        # acquires only that lane's barriers in that lane's arch router.
        self.coordinators: Dict[str, FleetReloadCoordinator] = {
            spec.model_id: FleetReloadCoordinator(
                spec.promoted_dir,
                self._router_for[spec.model_id],
                poll_interval_s=poll_interval_s,
                commit_timeout_s=commit_timeout_s,
                model_id=spec.model_id,
            )
            for spec in directory.lanes()
            if spec.promoted_dir is not None
        }
        self._count_lock = threading.Lock()
        self._lane_requests: Dict[str, int] = {  # graftlock: guarded-by=_count_lock
            mid: 0 for mid in self.lane_ids
        }
        self._lane_rejected: Dict[str, int] = {  # graftlock: guarded-by=_count_lock
            mid: 0 for mid in self.lane_ids
        }

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "TenantFleet":
        for router in self.routers.values():
            router.start()
        if self.watch:
            for coord in self.coordinators.values():
                coord.start()
        return self

    def stop(self) -> None:
        for coord in self.coordinators.values():
            coord.stop()
        for router in self.routers.values():
            router.stop()

    def __enter__(self) -> "TenantFleet":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- client side -----------------------------------------------------

    def router_for(self, model_id: str) -> FleetRouter:
        """The arch-group router serving ``model_id`` (did-you-mean on
        unknown lanes, as ``ValueError`` — the frontend's 400 class)."""
        try:
            self.directory.get(model_id)
        except KeyError as e:
            raise ValueError(str(e)) from None
        return self._router_for[model_id]

    def submit(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
        on_result: Optional[Any] = None,
        trace_id: Optional[str] = None,
        slo_class: Optional[str] = None,
        model_id: Optional[str] = None,
    ) -> Any:
        """Route one request down its lane. ``model_id`` is required
        (this IS the multi-tenant surface); ``slo_class=None`` defaults
        to the lane's declared class. Backpressure is per-lane: a
        rejection carries the LANE's Retry-After, and only that lane's
        counter moves."""
        if model_id is None:
            raise ValueError(
                "model_id is required on a tenant fleet; declared "
                f"lanes: {sorted(self.lane_ids)}"
            )
        router = self.router_for(model_id)
        spec = self.directory.get(model_id)
        with self._count_lock:
            self._lane_requests[model_id] += 1
        try:
            return router.submit(
                obs,
                deterministic=deterministic,
                timeout_s=timeout_s,
                on_result=on_result,
                trace_id=trace_id,
                slo_class=spec.slo_class if slo_class is None else slo_class,
                model_id=model_id,
            )
        except BackpressureError:
            with self._count_lock:
                self._lane_rejected[model_id] += 1
            raise

    # -- observability ---------------------------------------------------

    @property
    def replicas(self) -> List[Any]:
        return [r for router in self.routers.values() for r in router.replicas]

    @property
    def healthy_replicas(self) -> int:
        return sum(
            router.healthy_replicas for router in self.routers.values()
        )

    def lane_steps(self) -> Dict[str, int]:
        """Per-lane served step across every arch group — each lane
        monotonic independently."""
        steps: Dict[str, int] = {}
        for router in self.routers.values():
            steps.update(router.lane_steps())
        return steps

    def snapshot(self) -> Dict[str, float]:
        """One flat dict over every arch group. Merge discipline:
        ``model_{id}__*`` keys pass through (globally unique — lane
        names are), ``*_total`` counters and fleet widths SUM, and the
        rest (latency percentiles, per-replica gauges, rung receipts)
        take the MAX — a conservative worst-case when arch groups share
        a key (replica indices restart per group). Adds the fleet's own
        per-lane request/reject counters, which obs/export.py folds
        into ``model``-labeled families."""
        snap: Dict[str, float] = {}
        summed = (
            "fleet_replicas",
            "fleet_healthy_replicas",
            "fleet_estimated_drain_s",
        )
        for router in self.routers.values():
            for key, value in router.snapshot().items():
                if key.startswith("model_") and "__" in key:
                    snap[key] = value
                elif key.endswith("_total") or key in summed:
                    snap[key] = snap.get(key, 0.0) + value
                elif key not in snap or value > snap[key]:
                    snap[key] = value
        steps = self.lane_steps()
        snap["model_step"] = float(max(steps.values()))
        with self._count_lock:
            for mid in self.lane_ids:
                snap[f"model_{mid}__requests_total"] = float(
                    self._lane_requests[mid]
                )
                snap[f"model_{mid}__rejected_total"] = float(
                    self._lane_rejected[mid]
                )
        return snap

    def compile_counts(self) -> Dict[str, Dict[int, Dict[int, int]]]:
        """Per arch group, per replica, per rung trace counts."""
        return {
            arch: router.compile_counts()
            for arch, router in self.routers.items()
        }

    def shared_rung_compiles(self) -> Dict[str, int]:
        """The executable-sharing receipt: ``{"{arch}:rung{b}": count}``
        where count is the MAX compiles any replica in the group paid
        for that rung. Every value must be <= 1 — N same-arch lanes
        share one compile per (arch, rung), and each distinct arch pays
        its own single compile."""
        out: Dict[str, int] = {}
        for arch, router in self.routers.items():
            for counts in router.compile_counts().values():
                for bucket, count in counts.items():
                    key = f"{arch}:rung{bucket}"
                    out[key] = max(out.get(key, 0), int(count))
        return out

    def warmup(self) -> None:
        """Compile every rung in every arch group once, before traffic.
        One warmup per GROUP (not per lane) — the proof of sharing is
        that no lane's traffic adds compiles afterward."""
        for arch, specs in self.directory.arch_groups().items():
            warmup_fleet(self.routers[arch], (specs[0].obs_dim,))


def tenant_fleet_from_directory(
    directory: TenantDirectory,
    poll_interval_s: float = 2.0,
    **fleet_kwargs: Any,
) -> TenantFleet:
    """Build a :class:`TenantFleet` serving each lane's newest promoted
    checkpoint — the multi-tenant twin of ``fleet_from_checkpoint_dir``.
    Every lane must declare a ``promoted_dir`` holding at least one
    checkpoint (its coordinator then watches the same directory)."""
    from marl_distributedformation_tpu.compat.policy import LoadedPolicy
    from marl_distributedformation_tpu.utils.checkpoint import (
        checkpoint_step,
        latest_checkpoint,
    )

    policies: Dict[str, Any] = {}
    steps: Dict[str, int] = {}
    for spec in directory.lanes():
        if spec.promoted_dir is None:
            raise ValueError(
                f"lane {spec.model_id!r} declares no promoted_dir; "
                "tenant_fleet_from_directory seeds every lane from its "
                "newest promoted checkpoint"
            )
        path = latest_checkpoint(Path(spec.promoted_dir))
        if path is None:
            raise FileNotFoundError(
                f"lane {spec.model_id!r}: no rl_model_*_steps.msgpack "
                f"checkpoint under {spec.promoted_dir} to serve"
            )
        policies[spec.model_id] = LoadedPolicy.from_checkpoint(
            path, act_dim=spec.act_dim, env_params=spec.env_params()
        )
        steps[spec.model_id] = checkpoint_step(path)
    return TenantFleet(
        directory,
        policies,
        steps=steps,
        poll_interval_s=poll_interval_s,
        **fleet_kwargs,
    )
