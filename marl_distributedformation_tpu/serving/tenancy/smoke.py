"""Tenant smoke storm: the multi-tenant acceptance evidence in one report.

The fleet smoke (serving/fleet/smoke.py) proves routing + failover +
global step monotonicity for ONE model; this storm drives EVERY lane of
a :class:`~.fleet.TenantFleet` at once and reports the three numbers
that define tenant isolation:

- ``tenant_isolation_p95_ratio`` — each quiet lane's interactive p95
  during a batch storm on ANOTHER lane, over its own pre-storm
  baseline p95 (the worst such ratio across quiet lanes). Per-lane
  admission means a storm on lane A costs lane B queueing NOTHING —
  the ratio should stay near 1, and the quiet lanes must see zero
  rejections.
- ``model_{id}__step_monotonic_violations`` — per-LANE step
  monotonicity in response completion order, recorded via the
  router's ``on_result`` hook (inside the serving replica's
  batch-barrier region, so the log provably orders against lane
  swaps). Each lane is monotonic independently; a mid-storm swap of
  one lane must not wiggle any other lane's steps.
- ``shared_rung_compiles`` — the executable-sharing census:
  max compiles per (arch, rung) across every replica. <= 1 everywhere
  means N same-arch lanes rode one set of compiled rungs and each
  distinct arch paid exactly its own budget-1 compile.

``mid_storm`` is the chaos hook, fired once during the storm phase on
its own thread — the e2e test lands a one-lane coordinated swap there.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    RequestTimeout,
)
from marl_distributedformation_tpu.serving.smoke import DEFAULT_SIZES


class _LaneLog:
    """One lane's storm bookkeeping (lock-shared across its clients)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.ok = 0
        self.rejected = 0
        self.timed_out = 0
        self.failed = 0
        self.latencies_baseline: List[float] = []
        self.latencies_storm: List[float] = []
        self.completion_steps: List[int] = []

    def record_step(self, result: Any) -> None:
        with self.lock:
            self.completion_steps.append(int(result.model_step))

    def monotonic_violations(self) -> int:
        violations, high = 0, None
        for step in self.completion_steps:
            if high is not None and step < high:
                violations += 1
            high = step if high is None else max(high, step)
        return violations


def _p95(samples: Sequence[float]) -> float:
    ordered = sorted(samples)
    if not ordered:
        return 0.0
    idx = min(len(ordered) - 1, int(round(0.95 * (len(ordered) - 1))))
    return ordered[idx]


def run_tenant_smoke(
    fleet: Any,
    sizes: Sequence[int] = DEFAULT_SIZES,
    duration_s: float = 2.0,
    clients_per_lane: int = 2,
    storm_lane: Optional[str] = None,
    storm_clients: int = 4,
    deterministic: bool = True,
    seed: int = 0,
    mid_storm: Optional[Callable[[], None]] = None,
    mid_storm_at_s: float = 0.25,
    warmup: bool = True,
) -> Dict[str, Any]:
    """Drive every lane concurrently; when ``storm_lane`` is set, run a
    baseline phase (all lanes interactive) then a storm phase (the same
    traffic plus ``storm_clients`` batch loops hammering that one lane)
    and report the isolation ratio between them. Rejections and
    timeouts are measured, not raised."""
    if warmup:
        fleet.warmup()
    logs: Dict[str, _LaneLog] = {mid: _LaneLog() for mid in fleet.lane_ids}
    obs_dim = {
        spec.model_id: spec.obs_dim for spec in fleet.directory.lanes()
    }
    stop_at = [0.0]  # rebound per phase; clients read through the cell

    def loop(
        mid: str,
        idx: int,
        slo_class: str,
        sink: Callable[[_LaneLog, float], None],
    ) -> None:
        log = logs[mid]
        rng = np.random.default_rng(seed + 7919 * idx)
        i = idx
        while time.perf_counter() < stop_at[0]:
            n = int(sizes[i % len(sizes)])
            i += 1
            obs = rng.standard_normal(
                (n, obs_dim[mid]), dtype=np.float32
            )
            t0 = time.perf_counter()
            try:
                future = fleet.submit(
                    obs,
                    deterministic=deterministic,
                    on_result=log.record_step,
                    slo_class=slo_class,
                    model_id=mid,
                )
                future.result(timeout=fleet.default_timeout_s + 5.0)
            except BackpressureError as e:
                with log.lock:
                    log.rejected += 1
                time.sleep(min(0.05, e.retry_after_s))
                continue
            except (RequestTimeout, TimeoutError, FutureTimeoutError):
                with log.lock:
                    log.timed_out += 1
                continue
            except Exception:  # noqa: BLE001 — measured, not raised
                with log.lock:
                    log.failed += 1
                continue
            with log.lock:
                log.ok += 1
                sink(log, time.perf_counter() - t0)

    def run_phase(
        phase_s: float,
        sink: Callable[[_LaneLog, float], None],
        storm: bool,
    ) -> float:
        threads = [
            threading.Thread(
                target=loop, args=(mid, c, "interactive", sink),
                daemon=True,
            )
            for mid in fleet.lane_ids
            for c in range(clients_per_lane)
        ]
        if storm:
            threads.extend(
                threading.Thread(
                    target=loop,
                    args=(
                        storm_lane,
                        clients_per_lane + c,
                        "batch",
                        sink,
                    ),
                    daemon=True,
                )
                for c in range(storm_clients)
            )
        chaos = None
        if storm and mid_storm is not None:

            def _chaos() -> None:
                time.sleep(mid_storm_at_s)
                mid_storm()

            chaos = threading.Thread(target=_chaos, daemon=True)
        t0 = time.perf_counter()
        stop_at[0] = t0 + phase_s
        for t in threads:
            t.start()
        if chaos is not None:
            chaos.start()
        for t in threads:
            t.join(timeout=phase_s + 30.0)
        if chaos is not None:
            chaos.join(timeout=30.0)
        return time.perf_counter() - t0

    if storm_lane is not None:
        if storm_lane not in logs:
            raise ValueError(
                f"storm_lane {storm_lane!r} is not a declared lane: "
                f"{sorted(logs)}"
            )
        baseline_s = run_phase(
            duration_s / 2,
            lambda log, dt: log.latencies_baseline.append(dt),
            storm=False,
        )
        storm_s = run_phase(
            duration_s / 2,
            lambda log, dt: log.latencies_storm.append(dt),
            storm=True,
        )
        elapsed = baseline_s + storm_s
    else:
        elapsed = run_phase(
            duration_s,
            lambda log, dt: log.latencies_baseline.append(dt),
            storm=False,
        )

    report: Dict[str, Any] = dict(fleet.snapshot())
    report["duration_s"] = round(elapsed, 3)
    total_ok = 0
    for mid, log in logs.items():
        total_ok += log.ok
        report[f"model_{mid}__requests_ok"] = float(log.ok)
        report[f"model_{mid}__rejected"] = float(log.rejected)
        report[f"model_{mid}__timed_out"] = float(log.timed_out)
        report[f"model_{mid}__failed"] = float(log.failed)
        report[f"model_{mid}__requests_per_sec"] = (
            log.ok / elapsed if elapsed > 0 else 0.0
        )
        report[f"model_{mid}__step_monotonic_violations"] = float(
            log.monotonic_violations()
        )
        if log.completion_steps:
            report[f"model_{mid}__step_min"] = float(
                min(log.completion_steps)
            )
            report[f"model_{mid}__step_max"] = float(
                max(log.completion_steps)
            )
    report["requests_per_sec_fleet"] = (
        total_ok / elapsed if elapsed > 0 else 0.0
    )
    if storm_lane is not None:
        # Worst quiet-lane degradation: storm-phase p95 over its own
        # baseline p95. Floored at one scheduler window so a
        # near-zero baseline can't turn measurement noise into a
        # scary ratio.
        floor_s = 2e-3
        worst = 1.0
        for mid, log in logs.items():
            if mid == storm_lane:
                continue
            base = max(_p95(log.latencies_baseline), floor_s)
            storm_p95 = max(_p95(log.latencies_storm), floor_s)
            worst = max(worst, storm_p95 / base)
        report["tenant_isolation_p95_ratio"] = worst
        report["storm_lane"] = storm_lane
    shared = fleet.shared_rung_compiles()
    report["shared_rung_compiles"] = dict(shared)
    report["max_shared_rung_compiles"] = float(
        max(shared.values()) if shared else 0.0
    )
    return report
