"""TenantDirectory: the declared set of named model lanes.

One fleet, many models. A :class:`TenantSpec` names a lane — which
environment its policies act in, which architecture they are, what SLO
class its traffic defaults to, and which ``promoted/`` directory its
always-learning pipeline publishes into. The :class:`TenantDirectory`
is the fail-fast registry over those lanes (the same did-you-mean
discipline as ``envs.get_env``) plus the ARCH GROUPING the fleet builds
from: lanes whose ``(policy, hidden, obs_dim, act_dim)`` signature
matches share one set of compiled rung executables — their params are
traced inputs — while distinct architectures get their own engines and
their own budget-1 compile receipts.

Lane names become Prometheus label values and ``model_{id}__{metric}``
snapshot keys (obs/export.py folds on the FIRST double underscore), so
``model_id`` is restricted to ``[A-Za-z0-9_.-]`` without a ``__`` run —
the grammar stays unambiguous no matter the name.
"""

from __future__ import annotations

import dataclasses
import difflib
import re
from pathlib import Path
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

from marl_distributedformation_tpu.serving.scheduler import SLO_CLASSES

_MODEL_ID_OK = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]*$")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One named model lane.

    Args:
      model_id: the lane's name — rides requests, responses, promotion
        log lines (schema 5), and the ``model`` Prometheus label.
      env: environment the lane's policies act in (``envs`` registry
        name); decides the observation row shape and therefore the
        architecture group.
      policy: policy architecture class name (``compat.policy``
        registry: MLPActorCritic / CTDEActorCritic / GNNActorCritic).
      hidden: the architecture's hidden-layer widths (part of the arch
        signature — two MLPs of different widths do NOT share
        executables).
      slo_class: default admission class for this lane's traffic when a
        request does not say ("interactive" or "batch").
      promoted_dir: the lane's always-learning ``promoted/`` directory;
        its lane-keyed reload coordinator watches this. ``None`` = a
        static lane (seeded once, never hot-swapped).
      num_agents: optional env override (changes ``obs_dim`` and hence
        the arch group).
      act_dim: action dimensionality.
      max_queue: optional per-lane admission bound override (default:
        the fleet's ``tenant_max_queue``).
    """

    model_id: str
    env: str = "formation"
    policy: str = "MLPActorCritic"
    hidden: Tuple[int, ...] = (64, 64)
    slo_class: str = "interactive"
    promoted_dir: Optional[Path] = None
    num_agents: Optional[int] = None
    act_dim: int = 2
    max_queue: Optional[int] = None

    def __post_init__(self) -> None:
        if not _MODEL_ID_OK.match(self.model_id) or "__" in self.model_id:
            raise ValueError(
                f"bad model_id {self.model_id!r}: must match "
                f"{_MODEL_ID_OK.pattern} with no '__' (it becomes a "
                "metric label and a model_{id}__{metric} snapshot key)"
            )
        if self.slo_class not in SLO_CLASSES:
            raise ValueError(
                f"lane {self.model_id!r}: unknown slo_class "
                f"{self.slo_class!r}; known: {SLO_CLASSES}"
            )
        from marl_distributedformation_tpu.compat.policy import (
            POLICY_REGISTRY,
        )

        if self.policy not in POLICY_REGISTRY:
            raise ValueError(
                f"lane {self.model_id!r}: unknown policy {self.policy!r}; "
                f"known: {sorted(POLICY_REGISTRY)}"
            )
        object.__setattr__(self, "hidden", tuple(self.hidden))
        if self.promoted_dir is not None:
            object.__setattr__(
                self, "promoted_dir", Path(self.promoted_dir)
            )
        # Fail fast on a misspelled env name at DECLARATION time (the
        # registry's did-you-mean error), not at first request.
        self.env_params()

    def env_params(self) -> Any:
        """The lane's environment params (the env registry's defaults
        with this lane's overrides) — what the fleet builder hands to
        ``LoadedPolicy.from_checkpoint``."""
        from marl_distributedformation_tpu import envs

        overrides = (
            {} if self.num_agents is None
            else {"num_agents": self.num_agents}
        )
        return envs.get_env(self.env).default_params(**overrides)

    @property
    def obs_dim(self) -> int:
        return int(self.env_params().obs_dim)

    def arch_key(self) -> str:
        """The executable-sharing signature: lanes with equal keys serve
        through ONE engine per replica (shared compiled rungs); distinct
        keys get their own engines and budget-1 receipts."""
        widths = "x".join(str(w) for w in self.hidden)
        return (
            f"{self.policy}_h{widths}_obs{self.obs_dim}"
            f"_act{self.act_dim}"
        )


class TenantDirectory:
    """Ordered, fail-fast registry of :class:`TenantSpec` lanes."""

    def __init__(self, specs: Iterable[TenantSpec] = ()) -> None:
        self._lanes: Dict[str, TenantSpec] = {}
        for spec in specs:
            self.add(spec)

    def add(self, spec: TenantSpec) -> TenantSpec:
        if spec.model_id in self._lanes:
            raise ValueError(
                f"duplicate model_id {spec.model_id!r} in directory"
            )
        self._lanes[spec.model_id] = spec
        return spec

    def get(self, model_id: str) -> TenantSpec:
        """Fail-fast lookup with a did-you-mean hint — the same
        contract as ``envs.get_env``."""
        try:
            return self._lanes[model_id]
        except KeyError:
            close = difflib.get_close_matches(
                str(model_id), list(self._lanes), n=1
            )
            hint = f" (did you mean {close[0]!r}?)" if close else ""
            raise KeyError(
                f"unknown model_id {model_id!r}{hint}; declared lanes: "
                f"{sorted(self._lanes)}"
            ) from None

    def lanes(self) -> Tuple[TenantSpec, ...]:
        return tuple(self._lanes.values())

    def arch_groups(self) -> Dict[str, List[TenantSpec]]:
        """Lanes grouped by executable-sharing signature, declaration
        order preserved within each group."""
        groups: Dict[str, List[TenantSpec]] = {}
        for spec in self._lanes.values():
            groups.setdefault(spec.arch_key(), []).append(spec)
        return groups

    def __contains__(self, model_id: object) -> bool:
        return model_id in self._lanes

    def __iter__(self) -> Iterator[str]:
        return iter(self._lanes)

    def __len__(self) -> int:
        return len(self._lanes)
