"""TPU-native policy inference serving (the north-star's missing layer).

Training produces checkpoints; until now the only inference paths were
the offline ``eval.py`` rollout harness and the per-call, unbatched
``compat.policy.LoadedPolicy.predict``. This package serves those
checkpoints to concurrent callers the way Podracer (arXiv:2104.06272)
serves actors — large fixed-shape batched inference that keeps the
accelerator saturated — with the host-side request path JaxMARL
(arXiv:2311.10090) shows becomes the bottleneck once the policy itself
is compiled:

- :class:`~.engine.BucketedPolicyEngine` — donated, jit-compiled act
  functions over a small ladder of bucketed batch shapes; arbitrary
  request sizes pad to the next bucket so each bucket compiles exactly
  once (pinned by ``analysis.guards.RetraceGuard``).
- :class:`~.scheduler.MicroBatchScheduler` — bounded request queue that
  coalesces concurrent requests within a deadline window, with
  backpressure (reject-with-retry-after) and per-request timeouts.
- :class:`~.registry.ModelRegistry` — watches a ``logs/{name}/``
  directory via ``utils.checkpoint.latest_checkpoint`` and hot-swaps new
  checkpoints atomically between batches; in-flight requests finish on
  the params they were dispatched with.
- :class:`~.metrics.ServingMetrics` — queue depth, batch occupancy,
  latency percentiles, swap count; emitted through
  ``utils.logging.MetricsLogger``.
- :class:`~.client.ServingClient` — the in-process client (used by tests
  and the ``scripts/serve_policy.py`` smoke benchmark), duck-typed over
  one scheduler or a whole fleet router.
- ``serving.fleet`` — the multi-replica layer: ``FleetRouter`` (one
  replica per local device, queue-depth routing, circuit breaking +
  failover), ``FleetReloadCoordinator`` (poll-once batch-barrier swap,
  globally step-monotonic), ``FleetFrontend`` (stdlib HTTP/JSON),
  ``FleetMetrics``, ``run_fleet_smoke``.
- :class:`~.sharded.ShardedPolicyEngine` — the big rungs over a device
  mesh slice instead of per-device replicas: partition-rule-driven
  param placement (``match_partition_rules`` /
  ``make_shard_and_gather_fns``), batch-axis request sharding, optional
  bf16 rungs. ``ShardedSpec`` plugs it into a ``FleetRouter``.
- ``serving.tenancy`` — named model lanes over one fleet:
  ``TenantDirectory`` declares lanes (env, architecture, SLO class,
  promoted dir), ``TenantFleet`` serves them — same-arch lanes share
  compiled rung executables, per-lane admission queues, per-lane
  reload coordinators with per-model step monotonicity,
  ``run_tenant_smoke`` for the isolation evidence.
- ``serving.loadgen`` / ``serving.autotune`` — the earned ladder:
  open-loop traffic replay measuring req/s AT a p95 target
  (``max_rate_at_slo``), and a deterministic ladder autotuner deriving
  rungs + coalescing window from the observed distribution
  (``autotune_ladder``). SLO classes ride admission control —
  batch-eval traffic yields to interactive under backpressure
  (``MicroBatchScheduler.submit(slo_class=...)``).
- ``serving.elastic`` — the live capacity loop: ``TraceRecorder``
  captures offered arrivals at the schedulers, ``CapacityController``
  replays the window through the same autotune DP and re-splits the
  fleet (new ladder, new replicated/sharded device split) with
  prewarm-then-commit at the fleet batch barrier.

Architecture, bucket-ladder sizing, backpressure semantics, and the
hot-reload contract are documented in ``docs/serving.md``.
"""

from marl_distributedformation_tpu.serving.autotune import (
    LadderPlan,
    autotune_ladder,
    plans_equivalent,
    replay_recorder,
)
from marl_distributedformation_tpu.serving.client import (
    ServingClient,
    backoff_s,
)
from marl_distributedformation_tpu.serving.engine import (
    DEFAULT_BUCKETS,
    BucketedPolicyEngine,
)
from marl_distributedformation_tpu.serving.elastic import (
    CapacityController,
    CapacityDecision,
)
from marl_distributedformation_tpu.serving.loadgen import (
    RequestTrace,
    TraceRecorder,
    max_rate_at_slo,
    run_load,
    synthetic_trace,
)
from marl_distributedformation_tpu.serving.metrics import ServingMetrics
from marl_distributedformation_tpu.serving.registry import ModelRegistry
from marl_distributedformation_tpu.serving.scheduler import (
    SLO_BATCH,
    SLO_INTERACTIVE,
    BackpressureError,
    MicroBatchScheduler,
    RequestTimeout,
    ServedResult,
)
from marl_distributedformation_tpu.serving.sharded import (
    ShardedPolicyEngine,
    ShardedSpec,
)
from marl_distributedformation_tpu.serving.smoke import run_smoke_benchmark

__all__ = [
    "BackpressureError",
    "BucketedPolicyEngine",
    "CapacityController",
    "CapacityDecision",
    "DEFAULT_BUCKETS",
    "LadderPlan",
    "MicroBatchScheduler",
    "ModelRegistry",
    "RequestTimeout",
    "RequestTrace",
    "SLO_BATCH",
    "SLO_INTERACTIVE",
    "ServedResult",
    "ServingClient",
    "ServingMetrics",
    "ShardedPolicyEngine",
    "ShardedSpec",
    "TraceRecorder",
    "autotune_ladder",
    "backoff_s",
    "max_rate_at_slo",
    "plans_equivalent",
    "replay_recorder",
    "run_load",
    "run_smoke_benchmark",
    "synthetic_trace",
]
