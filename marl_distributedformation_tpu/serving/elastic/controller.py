"""Elastic capacity: a live control loop that re-splits devices and
re-derives the rung ladder under traffic.

The fleet boots with a capacity split chosen before traffic: how many
devices run replicated small-rung replicas, whether a mesh slice owns
the big rungs, which rungs exist, how long the coalescing window waits.
PR 11's autotuner made those choices *earned* from a trace — but only
offline. This module closes the loop:

1. **Observe** — the gauges the fleet already exports: the live
   :class:`~..loadgen.TraceRecorder` ring (offered sizes + arrival
   times, captured at ``MicroBatchScheduler.submit`` BEFORE admission
   control so overload is visible), per-replica queue depths, and the
   program ledger's double-residency swap watermark as the headroom
   bound for building new engines next to old ones.
2. **Decide** — replay the recorded window through the EXACT offline
   DP (:func:`~..autotune.replay_recorder`): same cost model, same
   determinism pin. :func:`~..autotune.plans_equivalent` is the
   hysteresis gate — a plan that would rebuild the same engines is not
   a decision, and every false re-split costs prewarm compiles plus a
   barrier pause.
3. **Apply, prewarm-then-commit** — build the new replicas OFF the
   serving path (params placed per the committed sharding rules, every
   rung compiled against REGISTRY params — the ``warmup_fleet``
   contract, since host-resident params would compile a different
   placement and trip the budget-1 guard), then land the membership
   swap at the existing fleet batch barrier
   (``FleetReloadCoordinator.commit_resplit``). No in-flight request
   ever sees a cold rung; ``model_step`` monotonicity is untouched
   (a prewarm the fleet stepped past is refused and redone). Retired
   replicas are de-routed at the commit, then drained and stopped
   AFTER the gates reopen — drain time never extends the pause.

The serving interruption a re-split costs is therefore exactly the
barrier-commit pause (``pause_ms`` in the apply report); prewarm
compiles happen before it and drains after it, both receipted in the
program ledger so a census diff can PROVE no compile ever rode the
request path (tests/test_elastic.py pins this).

Chaos seams (chaos/plane.py): ``elastic.prewarm`` aborts a round
before anything routes, ``elastic.commit`` fires inside the closed
barrier before the swap (old split intact), ``elastic.retire`` fires
in the drain worker after the new split already routes.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from marl_distributedformation_tpu.chaos.plane import fault_point
from marl_distributedformation_tpu.obs.ledger import get_ledger
from marl_distributedformation_tpu.serving.autotune import (
    LadderPlan,
    plans_equivalent,
    replay_recorder,
)
from marl_distributedformation_tpu.serving.sharded import ShardedSpec


@dataclasses.dataclass(frozen=True)
class CapacityDecision:
    """One re-split the controller intends to apply: the plan that
    earned it plus the concrete build recipe derived from it."""

    plan: LadderPlan
    replicated_count: int
    replicated_buckets: Tuple[int, ...]
    window_ms: float
    sharded_spec: Optional[ShardedSpec]
    sharded_min_rows: Optional[int]
    reason: str

    def to_dict(self) -> Dict[str, Any]:
        return {
            "replicated_count": self.replicated_count,
            "replicated_buckets": list(self.replicated_buckets),
            "window_ms": round(self.window_ms, 3),
            "sharded_buckets": (
                list(self.sharded_spec.buckets)
                if self.sharded_spec is not None
                else []
            ),
            "sharded_min_rows": self.sharded_min_rows,
            "reason": self.reason,
        }


def _tree_nbytes(params: Any) -> int:
    total = 0
    for leaf in _tree_leaves(params):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def _tree_leaves(params: Any) -> List[Any]:
    import jax

    return jax.tree_util.tree_leaves(params)


class CapacityController:
    """The live control loop over one fleet.

    Explicitly stepped (``step()``) or run as a background thread
    (``start(interval_s)`` / ``stop()``). Both paths serialize through
    ``_step_lock`` — two concurrent re-splits would race the barrier.

    Construction wires the loop to a running fleet::

        recorder = TraceRecorder()
        router = FleetRouter(..., trace_recorder=recorder)
        coordinator = FleetReloadCoordinator(router, ...)
        ctl = CapacityController(
            router, coordinator, row_shape=(obs_dim,),
            p95_target_ms=50.0,
        )
        report = ctl.step()   # None = no decision this round

    ``headroom_bytes``, when set, bounds prewarm: the ledger's swap
    watermark (the double-residency peak a commit provably reaches)
    plus the incoming engines' param bytes must fit under it, or the
    round is skipped — building capacity that OOMs the commit is worse
    than serving on yesterday's split.
    """

    def __init__(
        self,
        router: Any,
        coordinator: Any,
        row_shape: Tuple[int, ...],
        p95_target_ms: float,
        recorder: Any = None,
        min_requests: int = 64,
        max_rungs: int = 4,
        window_tol_ms: float = 1.0,
        headroom_bytes: Optional[float] = None,
        drain_timeout_s: float = 10.0,
        sharded_spec: Optional[ShardedSpec] = None,
        sharded_min_rows: Optional[int] = None,
        clear_after_decide: bool = True,
    ) -> None:
        self.router = router
        self.coordinator = coordinator
        self.row_shape = tuple(int(d) for d in row_shape)
        self.p95_target_ms = float(p95_target_ms)
        self.recorder = (
            recorder
            if recorder is not None
            else getattr(router, "trace_recorder", None)
        )
        if self.recorder is None:
            raise ValueError(
                "elastic control needs a TraceRecorder — pass one here "
                "or build the FleetRouter with trace_recorder="
            )
        self.min_requests = int(min_requests)
        self.max_rungs = int(max_rungs)
        self.window_tol_ms = float(window_tol_ms)
        self.headroom_bytes = headroom_bytes
        self.drain_timeout_s = float(drain_timeout_s)
        self.base_sharded_spec = sharded_spec or ShardedSpec()
        # Pins the replicated/sharded split point fed to the DP; None
        # lets autotune derive it from the size distribution.
        self.sharded_min_rows = sharded_min_rows
        # Each applied decision starts the next window fresh — a plan
        # re-derived from traffic the PREVIOUS split already answered
        # for would double-count it.
        self.clear_after_decide = bool(clear_after_decide)
        self._step_lock = threading.Lock()
        self._gauge_lock = threading.Lock()
        # The plan the serving split currently embodies (None until the
        # first commit: the boot split was not earned by this loop).
        self._current_plan: Optional[LadderPlan] = None  # graftlock: guarded-by=_step_lock
        self._counters: Dict[str, float] = {  # graftlock: guarded-by=_gauge_lock
            "elastic_resplits_committed": 0.0,
            "elastic_resplits_aborted": 0.0,
            "elastic_resplits_skipped": 0.0,
            "elastic_prewarm_compiles_total": 0.0,
            "elastic_last_pause_ms": 0.0,
            "elastic_last_prewarm_ms": 0.0,
        }
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self.last_error: Optional[str] = None
        self.reports: List[dict] = []  # graftlock: guarded-by=_gauge_lock

    # -- observe + decide ------------------------------------------------

    def decide(self) -> Optional[CapacityDecision]:
        """Replay the recorded window through the offline DP and turn
        the plan into a build recipe — or None when the window is too
        thin or the plan would rebuild what already serves."""
        devices = list(getattr(self.router, "_devices", []))
        n_dev = max(1, len(devices))
        plan = replay_recorder(
            self.recorder,
            self.p95_target_ms,
            min_requests=self.min_requests,
            max_rungs=self.max_rungs,
            mesh_divisor=n_dev if n_dev > 1 else 1,
            sharded_min_rows=self.sharded_min_rows,
        )
        if plan is None:
            return None
        if plans_equivalent(
            plan, self._current_plan, window_tol_ms=self.window_tol_ms
        ):
            self._bump("elastic_resplits_skipped")
            return None
        want_sharded = bool(plan.sharded_buckets) and n_dev > 1
        # Sharded slice spans every device; replicated replicas ride
        # alongside (max(1, D-1) keeps one device's worth of small-rung
        # capacity even under a pure big-rung storm — small stragglers
        # must not pad up to a mesh rung).
        replicated_count = max(1, n_dev - 1) if want_sharded else n_dev
        replicated_buckets = plan.replicated_buckets or plan.buckets
        spec = None
        sharded_min_rows = None
        if want_sharded:
            spec = self.base_sharded_spec.evolved(
                axis_sizes={"dp": n_dev},
                buckets=plan.sharded_buckets,
                window_ms=plan.sharded_window_ms,
            )
            sharded_min_rows = spec.route_min_rows
        return CapacityDecision(
            plan=plan,
            replicated_count=replicated_count,
            replicated_buckets=tuple(replicated_buckets),
            window_ms=plan.window_ms,
            sharded_spec=spec,
            sharded_min_rows=sharded_min_rows,
            reason=(
                f"ladder {list(plan.buckets)} @ window "
                f"{plan.window_ms:.2f}ms from {len(self.recorder)} "
                f"recorded arrivals ({plan.observed_rps:.1f} rps)"
            ),
        )

    def _headroom_ok(self, decision: CapacityDecision) -> bool:
        if self.headroom_bytes is None:
            return True
        params, _ = self.router.fleet_params()
        per_replica = _tree_nbytes(params)
        incoming = per_replica * (
            decision.replicated_count
            + (1 if decision.sharded_spec is not None else 0)
        )
        # The swap watermark already includes the double-residency peak
        # a commit reaches; the incoming engines stack on top of it
        # until the retired ones drain.
        watermark = get_ledger().watermark_bytes
        return (watermark + incoming) <= float(self.headroom_bytes)

    # -- prewarm ---------------------------------------------------------

    def prewarm(
        self, decision: CapacityDecision
    ) -> Tuple[List[Any], dict]:
        """Build + compile the decision's replicas OFF the serving
        path. Every rung warms against its registry's params (the
        ``warmup_fleet`` contract). Raises on an armed
        ``elastic.prewarm`` fault — the caller aborts the round and
        the old split keeps serving, untouched."""
        ledger = get_ledger()
        programs_before = len(ledger.entries())
        t0 = time.perf_counter()
        built: List[Any] = []
        for _ in range(decision.replicated_count):
            fault_point("elastic.prewarm")
            r = self.router.build_replica(
                buckets=decision.replicated_buckets,
                window_ms=decision.window_ms,
            )
            self._warm(r)
            built.append(r)
        if decision.sharded_spec is not None:
            fault_point("elastic.prewarm")
            r = self.router.build_sharded_replica(decision.sharded_spec)
            self._warm(r)
            built.append(r)
        report = {
            "prewarm_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "prewarm_programs_before": programs_before,
            "prewarm_programs_after": len(ledger.entries()),
        }
        report["prewarm_compiles"] = (
            report["prewarm_programs_after"] - programs_before
        )
        return built, report

    def _warm(self, replica: Any) -> None:
        params, _ = replica.registry.active()
        for bucket in replica.engine.buckets:
            replica.engine.act(
                np.zeros((bucket, *self.row_shape), np.float32),
                deterministic=True,
                nn_params=params,
            )

    # -- apply: prewarm, commit at the barrier, drain after --------------

    def apply(self, decision: CapacityDecision) -> dict:
        """One full re-split round. Returns a report dict; never
        raises. ``committed`` False means the old split still serves
        (prewarm fault, headroom refusal, stale prewarm, or a barrier
        abort — the report says which)."""
        report: dict = {
            "committed": False,
            "decision": decision.to_dict(),
        }
        if not self._headroom_ok(decision):
            report["skipped"] = "headroom"
            self._bump("elastic_resplits_skipped")
            return report
        try:
            built, prewarm_report = self.prewarm(decision)
        except Exception as e:  # noqa: BLE001 — contain, keep serving
            report["error"] = f"prewarm aborted: {e!r}"
            self._bump("elastic_resplits_aborted")
            return report
        report.update(prewarm_report)
        self._bump(
            "elastic_prewarm_compiles_total",
            float(prewarm_report["prewarm_compiles"]),
        )
        self._set_gauge(
            "elastic_last_prewarm_ms", prewarm_report["prewarm_ms"]
        )
        for r in built:
            r.scheduler.start()  # unrouted until the commit lands
        retiring = list(self.router.replicas)
        commit = self.coordinator.commit_resplit(
            add=built,
            retire=[r.index for r in retiring],
            sharded_min_rows=decision.sharded_min_rows,
        )
        report.update(commit)
        if not commit.get("committed"):
            for r in built:
                r.scheduler.stop()
            self._bump("elastic_resplits_aborted")
            return report
        self._set_gauge("elastic_last_pause_ms", commit["pause_ms"])
        # Gates are open again: drain the de-routed replicas off-path.
        drained = []
        for r in retiring:
            try:
                fault_point("elastic.retire")
                drained.append(
                    self.router.drain_replica(
                        r, timeout_s=self.drain_timeout_s
                    )
                )
            except Exception:  # noqa: BLE001 — injected retire fault
                # Stop undrained: queued requests surface
                # SchedulerStopped and fail over onto the new split.
                r.scheduler.stop()
                drained.append(False)
        report["drained_clean"] = int(sum(drained))
        report["retired_total"] = len(retiring)
        self._current_plan = decision.plan
        if self.clear_after_decide:
            self.recorder.clear()
        self._bump("elastic_resplits_committed")
        return report

    def step(self) -> Optional[dict]:
        """One control-loop tick: decide, then apply. Retries ONCE on
        a stale prewarm (a checkpoint reload landed mid-prewarm — the
        rebuilt replicas adopt the new step)."""
        with self._step_lock:
            decision = self.decide()
            if decision is None:
                return None
            report = self.apply(decision)
            if report.get("stale_prewarm"):
                report = self.apply(decision)
            with self._gauge_lock:
                self.reports.append(report)
            return report

    # -- background loop -------------------------------------------------

    def start(self, interval_s: float = 2.0) -> "CapacityController":
        if self._thread is not None:
            return self
        self._stop_evt.clear()

        def _loop() -> None:
            while not self._stop_evt.wait(interval_s):
                try:
                    self.step()
                except Exception as e:  # noqa: BLE001 — loop survives
                    self.last_error = repr(e)

        self._thread = threading.Thread(
            target=_loop, name="elastic-controller", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop_evt.set()
        self._thread.join(timeout=30.0)
        self._thread = None

    def __enter__(self) -> "CapacityController":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- observability ---------------------------------------------------

    def _bump(self, key: str, by: float = 1.0) -> None:
        with self._gauge_lock:
            self._counters[key] += by

    def _set_gauge(self, key: str, value: float) -> None:
        with self._gauge_lock:
            self._counters[key] = float(value)

    def snapshot(self) -> Dict[str, float]:
        with self._gauge_lock:
            return dict(self._counters)
