"""Elastic capacity: the live re-split control loop (controller.py)."""

from marl_distributedformation_tpu.serving.elastic.controller import (
    CapacityController,
    CapacityDecision,
)

__all__ = ["CapacityController", "CapacityDecision"]
