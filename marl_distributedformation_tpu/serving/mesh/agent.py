"""HostAgent: one host's control-plane presence in the mesh.

Runs beside the host's ``FleetRouter`` + ``FleetFrontend`` and does the
three things the data plane cannot:

- **membership** — registers with the coordinator and heartbeats on a
  lease, carrying the host's merged ``/v1/metrics`` snapshot as the
  gossip payload (one ``router.snapshot()`` per beat — the same dict
  the host's own ``GET /v1/metrics`` serves, so the mesh's routing view
  and the host's observability view can never disagree);
- **the barrier's host side** — serves ``mesh.prepare`` /
  ``mesh.commit`` / ``mesh.abort`` over a control-plane RPC endpoint,
  delegating to the fleet coordinator's staged two-phase split
  (``prepare_global`` stages + pauses, ``commit_prepared`` /
  ``abort_prepared`` resolve it). Round tokens guard against a stale
  coordinator: a commit for a round this host never staged is refused;
- **catch-up** — a heartbeat reply whose ``mesh_step`` is ahead of the
  local fleet means this host missed a commit (it was dead, or it
  joined late): the agent reloads the advertised checkpoint locally.
  Until that lands, the coordinator's routing view quarantines this
  host (stale step), so the catch-up can never serve an old
  ``model_step`` after newer responses.

The coordinator being unreachable NEVER stops the data plane: the agent
keeps serving and keeps retrying registration — availability of the
serving path outranks control-plane liveness.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from marl_distributedformation_tpu.chaos.plane import fault_point
from marl_distributedformation_tpu.obs import get_registry
from marl_distributedformation_tpu.serving.mesh.rpc import (
    JsonRpcServer,
    MeshRpcError,
    rpc_call,
)


class HostAgent:
    def __init__(
        self,
        host_id: str,
        router: Any,
        fleet: Any,  # FleetReloadCoordinator (the staged two-phase side)
        coordinator_url: str,
        data_url: str,
        host: str = "127.0.0.1",
        control_port: int = 0,
        heartbeat_interval_s: float = 0.5,
    ) -> None:
        self.host_id = host_id
        self.router = router
        self.fleet = fleet
        self.coordinator_url = coordinator_url
        self.data_url = data_url
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.registered = False
        self.beats_sent = 0
        self.catch_ups = 0
        self.catch_up_failures = 0
        self._catch_up_thread: Optional[threading.Thread] = None
        self._round: Optional[int] = None  # graftlock: guarded-by=_round_lock
        # The last resolved commit, kept for idempotency: a commit RPC
        # whose response was lost (client timeout racing a slow
        # install) is retried by the coordinator, and the retry must
        # report what actually happened — not refuse a round this host
        # already landed.
        self._committed: Optional[tuple] = None  # graftlock: guarded-by=_round_lock — (round, ok, step)
        self._round_lock = threading.Lock()
        self._server = JsonRpcServer(
            {
                "mesh.prepare": self._rpc_prepare,
                "mesh.commit": self._rpc_commit,
                "mesh.abort": self._rpc_abort,
                "mesh.ping": lambda payload: {
                    "host_id": self.host_id,
                    "step": int(self.fleet.fleet_step),
                },
            },
            host=host,
            port=control_port,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def control_url(self) -> str:
        return self._server.url

    # -- barrier host side (RPC handlers) --------------------------------

    def _rpc_prepare(self, payload: dict) -> dict:
        fault_point("mesh.prepare")
        round_id = int(payload["round"])
        step = payload.get("step")
        if step is not None and int(step) == int(self.fleet.fleet_step):
            # Already serving the round's target (a commit whose ack
            # was lost, or a catch-up that beat the round here): there
            # is nothing to stage OR pause — tell the coordinator to
            # count this host committed and move on.
            return {
                "staged": False,
                "already_at_step": True,
                "reason": f"already serving step {int(step)}",
                "round": round_id,
            }
        staged, reason = self.fleet.prepare_global(
            payload["path"],
            step=step,
            monotonic=bool(payload.get("monotonic", True)),
            trace_id=payload.get("trace_id"),
            ttl_s=float(payload.get("ttl_s", 60.0)),
        )
        with self._round_lock:
            self._round = round_id if staged else None
        return {"staged": staged, "reason": reason, "round": round_id}

    def _rpc_commit(self, payload: dict) -> dict:
        fault_point("mesh.commit")
        round_id = int(payload["round"])
        with self._round_lock:
            if self._committed is not None and self._committed[0] == round_id:
                # Idempotent retry: report what the first delivery did.
                return {
                    "ok": self._committed[1],
                    "step": self._committed[2],
                }
            if self._round != round_id:
                return {
                    "ok": False,
                    "reason": f"round {round_id} is not staged here "
                    f"(staged: {self._round})",
                }
            self._round = None
        ok = self.fleet.commit_prepared(trace_id=payload.get("trace_id"))
        with self._round_lock:
            self._committed = (round_id, ok, int(self.fleet.fleet_step))
        return {"ok": ok, "step": int(self.fleet.fleet_step)}

    def _rpc_abort(self, payload: dict) -> dict:
        with self._round_lock:
            self._round = None
        aborted = self.fleet.abort_prepared(
            str(payload.get("reason", "coordinator aborted the round"))
        )
        return {"ok": True, "aborted": aborted}

    # -- membership + gossip ---------------------------------------------

    def _beat_once(self) -> None:
        """One register-or-heartbeat round trip; transport failures are
        swallowed (the data plane must outlive the control plane) and
        surface only as ``registered=False`` until the coordinator
        answers again."""
        try:
            if not self.registered:
                reply = rpc_call(
                    self.coordinator_url,
                    "mesh.register",
                    {
                        "host_id": self.host_id,
                        "control_url": self.control_url,
                        "data_url": self.data_url,
                        "step": int(self.fleet.fleet_step),
                    },
                    timeout_s=self.heartbeat_interval_s * 4 + 1.0,
                )
                self.registered = bool(reply.get("registered"))
            else:
                reply = rpc_call(
                    self.coordinator_url,
                    "mesh.heartbeat",
                    {
                        "host_id": self.host_id,
                        "step": int(self.fleet.fleet_step),
                        "metrics": self._gossip_payload(),
                    },
                    timeout_s=self.heartbeat_interval_s * 4 + 1.0,
                )
                self.beats_sent += 1
                if not reply.get("registered"):
                    self.registered = False  # coordinator restarted
                    return
        except MeshRpcError:
            self.registered = False
            return
        self._maybe_catch_up(reply)

    def _gossip_payload(self) -> dict:
        """The host's merged metrics namespace — occupancy, queue
        depths, drain estimate, p95s — rides every heartbeat."""
        try:
            return self.router.snapshot()
        except Exception:  # noqa: BLE001 — gossip is advisory
            return {}

    def _maybe_catch_up(self, reply: dict) -> None:
        """A mesh_step ahead of the local fleet means this host missed
        a commit round — reload the advertised checkpoint locally, OFF
        the heartbeat thread: a restore + per-replica upload can take
        longer than the lease, and a host silenced by its own recovery
        would be spuriously declared dead mid-catch-up. One catch-up
        in flight at a time; failures cost a retry on a later beat,
        never the lane."""
        mesh_step = int(reply.get("mesh_step", -1))
        mesh_path = reply.get("mesh_path")
        if mesh_step <= int(self.fleet.fleet_step) or not mesh_path:
            return
        if (
            self._catch_up_thread is not None
            and self._catch_up_thread.is_alive()
        ):
            return  # already catching up; beats keep flowing

        def _do_catch_up() -> None:
            try:
                landed = self.fleet.reload_pinned(mesh_path)
            except Exception:  # noqa: BLE001 — retried on a later beat
                self.catch_up_failures += 1
                return
            if landed:
                self.catch_ups += 1
                get_registry().counter("mesh_catch_ups_total").inc()

        self._catch_up_thread = threading.Thread(
            target=_do_catch_up,
            name=f"mesh-catch-up-{self.host_id}",
            daemon=True,
        )
        self._catch_up_thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._beat_once()
            except Exception:  # noqa: BLE001 — the lane must outlive
                # any single beat; the lease taxonomy (not a dead
                # thread) owns declaring this host gone.
                self.registered = False
            self._stop.wait(self.heartbeat_interval_s)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "HostAgent":
        self._server.start()
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop,
                name=f"mesh-agent-{self.host_id}",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if deregister and self.registered:
            try:
                rpc_call(
                    self.coordinator_url,
                    "mesh.deregister",
                    {"host_id": self.host_id},
                    timeout_s=2.0,
                )
            except MeshRpcError:
                pass
        self._server.stop()

    def wait_registered(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.registered:
                return True
            time.sleep(0.02)
        return self.registered

    def __enter__(self) -> "HostAgent":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
