"""Loopback mesh: a real multi-process mesh on one machine.

The container's jaxlib refuses multi-process collectives, but the mesh
tier never needed them — the control plane coordinates over RPC and
the data plane over HTTP, both of which loopback exercises for real.
:func:`spawn_local_mesh` boots the whole topology the tests, the chaos
storm's ``--mesh`` campaign, and bench phase 14 share:

- a :class:`~.coordinator.MeshCoordinator` RPC service in THIS process,
- N host SUBPROCESSES (``serving/mesh/host.py`` — each its own
  interpreter, its own XLA backend, its own compiled engines; ``kill
  -9`` of one is a real host death),
- a :class:`~.router.MetaRouter` (+ optional :class:`~.router.
  MeshFrontend`) routing over them.

:func:`build_inprocess_host` is the thread-level twin for unit tests:
the same fleet + frontend + agent stack, wired over real loopback
HTTP/RPC, but inside the current process where the chaos plane and
assertions can reach it.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from marl_distributedformation_tpu.serving.mesh.coordinator import (
    MeshCoordinator,
)
from marl_distributedformation_tpu.serving.mesh.router import (
    MeshFrontend,
    MetaRouter,
)

REPO_ROOT = Path(__file__).resolve().parents[3]


class MeshHostProcess:
    """One spawned host subprocess plus its parsed ready line."""

    def __init__(self, proc: subprocess.Popen, info: Dict[str, Any]):
        self.proc = proc
        self.host_id = str(info["host_id"])
        self.data_url = str(info["data_url"])
        self.control_url = str(info["control_url"])
        self.pid = int(info["pid"])
        self.step = int(info.get("step", -1))

    def kill(self, sig: int = signal.SIGKILL) -> None:
        """A REAL host death — the failure mode SimulatedCrash only
        imitates."""
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            pass

    def alive(self) -> bool:
        return self.proc.poll() is None


class LocalMesh:
    """Handle over the whole loopback topology; ``stop()`` tears down
    hosts, router state, and the coordinator."""

    def __init__(
        self,
        coordinator: MeshCoordinator,
        router: MetaRouter,
        hosts: List[MeshHostProcess],
        frontend: Optional[MeshFrontend] = None,
    ) -> None:
        self.coordinator = coordinator
        self.router = router
        self.hosts = hosts
        self.frontend = frontend

    def kill_host(self, index: int, sig: int = signal.SIGKILL) -> str:
        self.hosts[index].kill(sig)
        return self.hosts[index].host_id

    def stop(self) -> None:
        if self.frontend is not None:
            self.frontend.stop()
        for h in self.hosts:
            if h.alive():
                h.proc.terminate()
        for h in self.hosts:
            try:
                h.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                h.proc.kill()
        self.coordinator.stop()

    def __enter__(self) -> "LocalMesh":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def spawn_host_process(
    promoted_dir: str | Path,
    coordinator_url: str,
    host_id: str,
    replicas: int = 1,
    buckets: Sequence[int] = (1, 8),
    obs_dim: Optional[int] = None,
    num_agents: Optional[int] = None,
    heartbeat_s: float = 0.25,
    fault_spec: Optional[List[dict]] = None,
    ready_timeout_s: float = 120.0,
    extra_args: Sequence[str] = (),
) -> MeshHostProcess:
    """Spawn one host subprocess and block until its ready line (the
    first import of jax + engine warmup dominate; the shared
    compilation cache makes repeats fast)."""
    cmd = [
        sys.executable,
        "-m",
        "marl_distributedformation_tpu.serving.mesh.host",
        "--promoted-dir", str(promoted_dir),
        "--coordinator-url", coordinator_url,
        "--host-id", host_id,
        "--replicas", str(replicas),
        "--buckets", ",".join(str(b) for b in buckets),
        "--heartbeat-s", str(heartbeat_s),
    ]
    if num_agents is not None:
        cmd += ["--num-agents", str(num_agents)]
    if obs_dim is not None:
        cmd += ["--obs-dim", str(obs_dim)]
    if fault_spec:
        cmd += ["--fault-spec", json.dumps(fault_spec)]
    cmd += list(extra_args)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = (
        str(REPO_ROOT) + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    proc = subprocess.Popen(
        cmd,
        cwd=str(REPO_ROOT),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL
        if os.environ.get("MESH_HOST_STDERR") != "1"
        else None,
        text=True,
    )
    import select

    deadline = time.monotonic() + ready_timeout_s
    line = ""
    while time.monotonic() < deadline:
        remaining = max(0.0, deadline - time.monotonic())
        readable, _, _ = select.select(
            [proc.stdout], [], [], min(remaining, 0.5)
        )
        if readable:
            line = proc.stdout.readline()
            if line:
                break
        if proc.poll() is not None:
            raise RuntimeError(
                f"mesh host {host_id} exited rc={proc.returncode} "
                "before its ready line (run with MESH_HOST_STDERR=1 "
                "for its stderr)"
            )
    if not line:
        proc.kill()
        raise TimeoutError(
            f"mesh host {host_id} produced no ready line in "
            f"{ready_timeout_s}s"
        )
    info = json.loads(line)
    if not info.get("ready"):
        proc.kill()
        raise RuntimeError(f"mesh host {host_id} not ready: {info}")
    return MeshHostProcess(proc, info)


def spawn_local_mesh(
    promoted_dir: str | Path,
    hosts: int = 2,
    replicas_per_host: int = 1,
    buckets: Sequence[int] = (1, 8),
    obs_dim: Optional[int] = None,
    num_agents: Optional[int] = None,
    heartbeat_s: float = 0.25,
    lease_s: float = 1.0,
    dead_after_s: float = 1.0,
    prepare_timeout_s: float = 30.0,
    frontend_port: Optional[int] = None,
    watch: bool = False,
    fault_specs: Optional[Dict[int, List[dict]]] = None,
    default_timeout_s: float = 10.0,
    max_failovers: int = 1,
    probe_interval_s: float = 1.0,
    ready_timeout_s: float = 120.0,
) -> LocalMesh:
    """Boot coordinator + N host subprocesses + MetaRouter, blocking
    until every host registered. ``watch=True`` also starts the
    coordinator's background poll of ``promoted_dir`` (the
    always-learning shape); tests usually drive ``refresh()``
    themselves. ``fault_specs`` maps a host index to the JSON fault
    list armed on that subprocess's chaos plane."""
    coordinator = MeshCoordinator(
        log_dir=promoted_dir,
        lease_s=lease_s,
        dead_after_s=dead_after_s,
        prepare_timeout_s=prepare_timeout_s,
    )
    if watch:
        coordinator.start()
    else:
        coordinator.serve()
    procs: List[MeshHostProcess] = []
    try:
        for i in range(hosts):
            procs.append(
                spawn_host_process(
                    promoted_dir,
                    coordinator.url,
                    host_id=f"host{i}",
                    replicas=replicas_per_host,
                    buckets=buckets,
                    obs_dim=obs_dim,
                    num_agents=num_agents,
                    heartbeat_s=heartbeat_s,
                    fault_spec=(fault_specs or {}).get(i),
                    ready_timeout_s=ready_timeout_s,
                )
            )
        deadline = time.monotonic() + ready_timeout_s
        while time.monotonic() < deadline:
            states = {h["host_id"] for h in coordinator.hosts()}
            if {p.host_id for p in procs} <= states:
                break
            time.sleep(0.05)
        else:
            raise TimeoutError(
                f"hosts never registered: have "
                f"{[h['host_id'] for h in coordinator.hosts()]}"
            )
    except BaseException:
        for p in procs:
            p.proc.kill()
        coordinator.stop()
        raise
    router = MetaRouter(
        coordinator,
        default_timeout_s=default_timeout_s,
        max_failovers=max_failovers,
        probe_interval_s=probe_interval_s,
    )
    frontend = None
    if frontend_port is not None:
        frontend = MeshFrontend(router, port=frontend_port).start()
    return LocalMesh(coordinator, router, procs, frontend)


def build_inprocess_host(
    promoted_dir: str | Path,
    coordinator_url: str,
    host_id: str,
    obs_dim: int,
    env_params: Any = None,
    act_dim: int = 2,
    replicas: int = 1,
    buckets: Sequence[int] = (1,),
    heartbeat_s: float = 0.2,
    devices: Optional[Sequence[Any]] = None,
    window_ms: float = 2.0,
):
    """The host stack inside the CURRENT process (thread-level tests):
    returns ``(router, fleet, frontend, agent)``, all started. The
    caller owns teardown (agent/frontend/router stop order)."""
    from marl_distributedformation_tpu.serving.fleet import (
        FleetFrontend,
        fleet_from_checkpoint_dir,
        warmup_fleet,
    )
    from marl_distributedformation_tpu.serving.mesh.agent import HostAgent

    router, fleet = fleet_from_checkpoint_dir(
        promoted_dir,
        env_params=env_params,
        act_dim=act_dim,
        num_replicas=replicas,
        buckets=tuple(buckets),
        devices=devices,
        window_ms=window_ms,
    )
    router.start()
    warmup_fleet(router, (obs_dim,))
    frontend = FleetFrontend(router).start()
    agent = HostAgent(
        host_id=host_id,
        router=router,
        fleet=fleet,
        coordinator_url=coordinator_url,
        data_url=frontend.url,
        heartbeat_interval_s=heartbeat_s,
    ).start()
    return router, fleet, frontend, agent
