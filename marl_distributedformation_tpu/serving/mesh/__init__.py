"""Fleet-of-fleets: the cross-host serving tier (docs/mesh.md).

Every serving invariant the repo earned stops at the process boundary;
this package carries them across it, the Podracer way (PAPERS.md): a
host tier layered above the per-host ``FleetRouter`` stacks, with the
CONTROL plane — not the data plane — doing the cross-host work.

- :class:`~.coordinator.MeshCoordinator` — stdlib RPC service owning
  the host registry, replica discovery, health gossip (leases +
  heartbeats, suspect -> dead taxonomy), and the **cross-host reload
  barrier**: a two-phase generalization of the fleet batch-barrier
  commit (prepare on every host, commit only when ALL hosts staged,
  abort-and-restore on any wedge/timeout), so ``model_step`` stays
  globally monotonic in response completion order ACROSS hosts. The
  pinned-reload/rollback exemption rides up unchanged.
- :class:`~.agent.HostAgent` — one host's control-plane presence:
  membership + the heartbeat gossip payload (the host's merged
  ``/v1/metrics`` namespace), the barrier's host side, and stale-host
  catch-up.
- :class:`~.router.MetaRouter` / :class:`~.router.MeshFrontend` — the
  host-tier frontend: routes by per-host estimated drain (gossiped
  ``fleet_estimated_drain_s``), circuit-breaks dead hosts with bounded
  cross-host failover of accepted requests, and propagates
  ``X-Trace-Id`` through the extra hop.
- :mod:`~.loopback` — the whole topology on one machine: coordinator +
  MetaRouter in-process, hosts as REAL subprocesses (``kill -9`` is a
  real host death). Testable without multi-process jax collectives,
  which this container's jaxlib refuses.
- :func:`~.smoke.run_mesh_smoke` — bench phase 14's harness: mesh
  req/s, global-swap latency, kill-one-host failover accounting, and
  per-host budget-1 compile receipts.

The always-learning pipeline promotes unchanged: the ``Promoter``
publishes ONCE into ``promoted/`` and the coordinator (duck-type
compatible with ``FleetReloadCoordinator``) drives the global commit;
``promotions.jsonl`` schema 4 records the round's host count.
"""

from marl_distributedformation_tpu.serving.mesh.agent import (  # noqa: F401
    HostAgent,
)
from marl_distributedformation_tpu.serving.mesh.coordinator import (  # noqa: F401,E501
    HOST_ALIVE,
    HOST_DEAD,
    HOST_SUSPECT,
    MeshCoordinator,
    MeshHost,
)
from marl_distributedformation_tpu.serving.mesh.loopback import (  # noqa: F401,E501
    LocalMesh,
    build_inprocess_host,
    spawn_host_process,
    spawn_local_mesh,
)
from marl_distributedformation_tpu.serving.mesh.router import (  # noqa: F401
    MeshFrontend,
    MeshResult,
    MetaRouter,
    NoHealthyHosts,
)
from marl_distributedformation_tpu.serving.mesh.rpc import (  # noqa: F401
    JsonRpcServer,
    MeshRpcError,
    MeshUnreachable,
    rpc_call,
)

__all__ = [
    "HOST_ALIVE",
    "HOST_DEAD",
    "HOST_SUSPECT",
    "HostAgent",
    "JsonRpcServer",
    "LocalMesh",
    "MeshCoordinator",
    "MeshFrontend",
    "MeshHost",
    "MeshResult",
    "MeshRpcError",
    "MeshUnreachable",
    "MetaRouter",
    "NoHealthyHosts",
    "build_inprocess_host",
    "rpc_call",
    "run_mesh_smoke",
    "spawn_host_process",
    "spawn_local_mesh",
]


def run_mesh_smoke(*args, **kwargs):
    """Lazy alias for :func:`~.smoke.run_mesh_smoke` (the smoke pulls
    in trainer machinery; importing the mesh package must not)."""
    from marl_distributedformation_tpu.serving.mesh.smoke import (
        run_mesh_smoke as _run,
    )

    return _run(*args, **kwargs)
