"""MetaRouter: the host-tier frontend above per-host FleetRouters.

One tier up from ``serving/fleet/router.py``, same three duties, now
over HTTP instead of in-process schedulers:

- **Route.** Every request goes to the routable host (coordinator
  health view: not dead, serving the mesh step) with the lowest
  estimated drain — the host's own gossiped ``fleet_estimated_drain_s``
  (queue depth x recent batch seconds, summed over its replicas, riding
  every heartbeat) plus a local in-flight penalty that covers the
  gossip staleness window. The per-host fleet router then does its own
  per-replica routing below — two tiers of the same join-the-shortest-
  TIME-queue rule.
- **Degrade.** A host that refuses connections or answers 503 is
  circuit-broken locally (and reported to the coordinator's health
  view); its accepted requests transparently fail over to surviving
  hosts, bounded by ``max_failovers`` extra hosts and the request's own
  deadline. Half-open probing readmits it: after ``probe_interval_s``
  the next routed request is the probe.
- **Reject honestly.** Only when EVERY routable host answers 429 does
  the MetaRouter raise :class:`BackpressureError` with the smallest
  ``retry_after_s`` any host quoted — the same contract as the fleet
  router and the single scheduler, so ``ServingClient`` works unchanged
  over a whole mesh.

``X-Trace-Id`` propagates through the extra hop: the MetaRouter sends
the caller's ID on the forwarded request, the host frontend echoes it
into its own dispatch spans, and the meta response carries it back —
one trace ID correlates client -> meta -> host -> replica -> batch.

:class:`MeshFrontend` is the HTTP door above :meth:`MetaRouter.submit`,
the same protocol as ``FleetFrontend`` (``/v1/act``, ``/v1/health``,
``/v1/metrics``) with ``host`` added to act responses.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import math
import socket
import threading
import time
import urllib.parse
from concurrent.futures import Future
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from marl_distributedformation_tpu.serving.mesh.rpc import (
    ThreadedHttpEndpoint,
    post_json,
)
from marl_distributedformation_tpu.obs import (
    PROMETHEUS_CONTENT_TYPE,
    TRACE_HEADER,
    get_registry,
    get_tracer,
    new_trace_id,
    prometheus_exposition,
    sanitize_trace_id,
    wants_prometheus,
)
from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    RequestTimeout,
)


class NoHealthyHosts(RuntimeError):
    """Every mesh host is dead or circuit-broken: the mesh is down."""


@dataclasses.dataclass
class MeshResult:
    """What a meta-routed request resolves to — ``ServedResult`` plus
    the host that answered and the echoed trace ID."""

    actions: np.ndarray
    model_step: int
    latency_s: float
    replica: int
    host: str
    trace_id: Optional[str] = None


class MetaRouter:
    """Drain-aware routing + circuit breaking over mesh hosts.

    Args:
      coordinator: the :class:`~.coordinator.MeshCoordinator` whose
        registry/health/gossip view this router reads (co-resident in
        the control-plane process — the data path never does RPC).
      default_timeout_s: request deadline when the caller names none.
      max_failovers: extra hosts one accepted request may be retried on
        after its first host fails mid-flight.
      probe_interval_s: how long a locally-broken host stays out of
        rotation before a half-open probe readmits it.
    """

    def __init__(
        self,
        coordinator: Any,
        default_timeout_s: float = 10.0,
        max_failovers: int = 1,
        probe_interval_s: float = 1.0,
    ) -> None:
        self.coordinator = coordinator
        self.default_timeout_s = float(default_timeout_s)
        self.max_failovers = int(max_failovers)
        self.probe_interval_s = float(probe_interval_s)
        self._lock = threading.Lock()
        self._broken: Dict[str, Tuple[float, str]] = {}  # graftlock: guarded-by=_lock — id -> (t, why)
        self._inflight: Dict[str, int] = {}  # graftlock: guarded-by=_lock
        self.routed_total = 0  # graftlock: guarded-by=_lock
        self.failed_over_total = 0  # graftlock: guarded-by=_lock
        self.rejected_total = 0  # graftlock: guarded-by=_lock
        self.breaks_total = 0  # graftlock: guarded-by=_lock
        self._routed_per_host: Dict[str, int] = {}  # graftlock: guarded-by=_lock

    # -- client side -----------------------------------------------------

    def submit(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
        trace_id: Optional[str] = None,
        slo_class: str = "interactive",
    ) -> Future:
        """Duck-type twin of ``FleetRouter.submit`` (the surface
        ``ServingClient`` and the pipeline's first-serve probe share):
        raises :class:`BackpressureError` / :class:`NoHealthyHosts` at
        submit time, resolves everything else through the future. The
        forward itself is synchronous on the calling thread — the
        frontend hands each request its own handler thread, and the
        blocking wait IS the request."""
        future: Future = Future()
        try:
            future.set_result(
                self.predict(
                    obs,
                    deterministic=deterministic,
                    timeout_s=timeout_s,
                    trace_id=trace_id,
                    slo_class=slo_class,
                )
            )
        except (BackpressureError, NoHealthyHosts):
            raise
        except Exception as e:  # noqa: BLE001 — typed through the future
            future.set_exception(e)
        return future

    def predict(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
        trace_id: Optional[str] = None,
        slo_class: str = "interactive",
    ) -> MeshResult:
        """Blocking meta-routed act. The failure taxonomy mirrors the
        fleet router's: BackpressureError when every routable host is
        full, NoHealthyHosts when none is routable, RequestTimeout past
        the deadline, ValueError for the caller's own malformed
        request."""
        timeout = (
            self.default_timeout_s if timeout_s is None else float(timeout_s)
        )
        deadline = time.perf_counter() + timeout
        trace_id = sanitize_trace_id(trace_id) or new_trace_id()
        body = json.dumps(
            {
                "obs": np.asarray(obs, np.float32).tolist(),
                "deterministic": bool(deterministic),
                "timeout_s": timeout,
                "slo_class": slo_class,
            }
        ).encode()
        tried: set = set()
        hops = 0
        rejections: List[float] = []
        while True:
            candidates = [
                h for h in self._eligible_hosts() if h.host_id not in tried
            ]
            if not candidates:
                break
            host = min(candidates, key=self._score)
            tried.add(host.host_id)
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                raise RequestTimeout(
                    f"deadline passed after trying {sorted(tried)}"
                )
            with self._lock:
                self._inflight[host.host_id] = (
                    self._inflight.get(host.host_id, 0) + 1
                )
            try:
                status, payload, echoed = self._forward(
                    host.data_url, body, trace_id, remaining
                )
            except (OSError, http.client.HTTPException) as e:
                # Nobody answered: the host-death signal. Break it,
                # fail the request over while the hop budget lasts.
                self._break(host.host_id, f"unreachable: {e!r}")
                if hops >= self.max_failovers:
                    raise NoHealthyHosts(
                        f"host {host.host_id} unreachable and failover "
                        f"budget spent: {e!r}"
                    ) from e
                hops += 1
                with self._lock:
                    self.failed_over_total += 1
                continue
            finally:
                with self._lock:
                    self._inflight[host.host_id] -= 1
            if status == 200:
                with self._lock:
                    self.routed_total += 1
                    self._routed_per_host[host.host_id] = (
                        self._routed_per_host.get(host.host_id, 0) + 1
                    )
                return MeshResult(
                    actions=np.asarray(payload["actions"], np.float32),
                    model_step=int(payload["model_step"]),
                    latency_s=float(payload.get("latency_s", 0.0)),
                    replica=int(payload.get("replica", -1)),
                    host=host.host_id,
                    trace_id=echoed or trace_id,
                )
            if status == 429:
                # That host is full, not broken — walk down the drain
                # ordering like the fleet router walks past full
                # replicas (no failover hop consumed).
                rejections.append(
                    float(payload.get("retry_after_s", 0.1))
                )
                continue
            if status == 400:
                raise ValueError(
                    str(payload.get("error", "bad request"))
                )
            if status == 504:
                raise RequestTimeout(
                    str(payload.get("error", "deadline passed"))
                )
            if status == 503:
                # The whole host fleet is down — circuit-break it and
                # keep WALKING (routing around a down host is routing,
                # not failover: no hop consumed). If every host ends
                # up broken this way, the loop exits with no
                # candidates and the typed NoHealthyHosts below keeps
                # the mesh-down taxonomy intact (a 503 everywhere must
                # never surface as a generic 500).
                self._break(
                    host.host_id,
                    f"503: {payload.get('error', 'fleet down')}",
                )
                continue
            # Other 5xx: the request is safely retryable (pure
            # inference) on another host while the hop budget lasts.
            if hops >= self.max_failovers:
                raise RuntimeError(
                    f"host {host.host_id} answered {status}: "
                    f"{payload.get('error', '')!r} (failover budget "
                    "spent)"
                )
            hops += 1
            with self._lock:
                self.failed_over_total += 1
        if rejections:
            with self._lock:
                self.rejected_total += 1
            raise BackpressureError(min(rejections))
        raise NoHealthyHosts(
            "no routable mesh host (all dead, stale, or circuit-broken)"
        )

    # -- transport -------------------------------------------------------

    @staticmethod
    def _forward(
        data_url: str,
        body: bytes,
        trace_id: str,
        timeout_s: float,
    ) -> Tuple[int, dict, Optional[str]]:
        """One ``POST /v1/act`` to a host frontend. Returns
        ``(status, payload, echoed_trace_id)``; transport errors raise
        OSError/HTTPException for the caller's failover logic. The
        wait slack mirrors the frontends' own: the host fails expired
        requests itself."""
        status, payload, headers = post_json(
            data_url,
            "/v1/act",
            body,
            headers={TRACE_HEADER: trace_id},
            timeout_s=timeout_s + 10.0,
        )
        return status, payload, headers.get(TRACE_HEADER)

    # -- routing state ---------------------------------------------------

    def _eligible_hosts(self) -> List[Any]:
        """Coordinator-routable hosts minus the locally-broken ones,
        with half-open readmission after ``probe_interval_s``."""
        now = time.monotonic()
        hosts = self.coordinator.routable_hosts()
        out = []
        with self._lock:
            for h in hosts:
                broken = self._broken.get(h.host_id)
                if broken is not None:
                    if now - broken[0] < self.probe_interval_s:
                        continue
                    del self._broken[h.host_id]  # half-open: next
                    # routed request is the probe; failure re-breaks
                out.append(h)
        return out

    def _score(self, host: Any) -> Tuple[float, int]:
        """Estimated drain from the host's gossip plus the local
        in-flight count (covers the gossip staleness window: two
        requests racing the same idle host must not both read 0)."""
        drain = 0.0
        metrics = getattr(host, "metrics", None) or {}
        try:
            drain = float(metrics.get("fleet_estimated_drain_s", 0.0))
        except (TypeError, ValueError):
            drain = 0.0
        with self._lock:
            inflight = self._inflight.get(host.host_id, 0)
        return (drain, inflight)

    def _break(self, host_id: str, reason: str) -> None:
        with self._lock:
            if host_id in self._broken:
                return
            self._broken[host_id] = (time.monotonic(), reason)
            self.breaks_total += 1
        # Feed the coordinator's health view: the data plane saw this
        # host dead before the lease did.
        try:
            self.coordinator.mark_dead(host_id, f"meta-router: {reason}")
        except Exception:  # noqa: BLE001 — local breaking still stands
            pass
        get_tracer().incident(
            "mesh_circuit_break", host=host_id, reason=reason
        )

    # -- observability ---------------------------------------------------

    @property
    def healthy_hosts(self) -> int:
        return len(self._eligible_hosts())

    def snapshot(self) -> Dict[str, float]:
        """Mesh-tier metrics: routing counters plus per-host health and
        the coordinator's registry view, flat floats like every other
        snapshot in the repo. Published into the process registry so
        the merged Prometheus namespace carries the mesh families."""
        hosts = self.coordinator.hosts()
        with self._lock:
            out: Dict[str, float] = {
                "mesh_hosts": float(len(hosts)),
                "mesh_routed_total": float(self.routed_total),
                "mesh_rejected_total": float(self.rejected_total),
                "mesh_failed_over_total": float(self.failed_over_total),
                "mesh_breaks_total": float(self.breaks_total),
                "mesh_step": float(self.coordinator.fleet_step),
                "mesh_commit_rounds": float(self.coordinator.commit_round),
            }
            routed = dict(self._routed_per_host)
            broken = set(self._broken)
        alive = 0
        for i, h in enumerate(sorted(hosts, key=lambda r: r["host_id"])):
            alive += int(
                h["state"] == "alive" and h["host_id"] not in broken
            )
            out[f"host{i}_routed"] = float(routed.get(h["host_id"], 0))
            out[f"host{i}_alive"] = float(h["state"] == "alive")
            out[f"host{i}_step"] = float(h["step"])
        out["mesh_hosts_routable"] = float(alive)
        get_registry().record_gauges(out)
        return out

    def host_compile_counts(self) -> Dict[str, Dict[str, float]]:
        """Per-host budget-1 receipts, scraped from each reachable
        host's ``/v1/metrics`` JSON (the ``rung*_compiles`` gauges its
        fleet already exports). Dead hosts are simply absent — they
        serve nothing, so they owe no receipt."""
        out: Dict[str, Dict[str, float]] = {}
        for h in self.coordinator.hosts():
            if h["state"] == "dead":
                continue
            parsed = urllib.parse.urlsplit(h["data_url"])
            conn = http.client.HTTPConnection(
                parsed.hostname, parsed.port, timeout=5.0
            )
            try:
                conn.request("GET", "/v1/metrics")
                resp = conn.getresponse()
                snap = json.loads(resp.read())
            except (OSError, ValueError, http.client.HTTPException):
                continue
            finally:
                conn.close()
            out[h["host_id"]] = {
                k: float(v)
                for k, v in snap.items()
                if k.endswith("_compiles")
            }
        return out


def _make_handler(router: MetaRouter):
    class _Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt: str, *args: Any) -> None:
            pass

        def _reply(
            self,
            status: int,
            payload: dict,
            retry_after_s: Optional[float] = None,
            trace_id: Optional[str] = None,
        ) -> None:
            if trace_id is not None:
                payload = {**payload, "trace_id": trace_id}
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if trace_id is not None:
                self.send_header(TRACE_HEADER, trace_id)
            if retry_after_s is not None:
                self.send_header(
                    "Retry-After", str(max(1, math.ceil(retry_after_s)))
                )
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass

        def do_GET(self) -> None:  # noqa: N802 — stdlib handler API
            if self.path == "/v1/health":
                routable = router.healthy_hosts
                self._reply(
                    200 if routable else 503,
                    {
                        "routable_hosts": routable,
                        "hosts": len(router.coordinator.hosts()),
                        "model_step": int(router.coordinator.fleet_step),
                    },
                )
            elif self.path == "/v1/metrics":
                snap = router.snapshot()
                if wants_prometheus(self.headers.get("Accept")):
                    from marl_distributedformation_tpu.obs.ledger import (
                        merge_ledger_snapshot,
                    )

                    merged = merge_ledger_snapshot(
                        get_registry().snapshot()
                    )
                    merged.update(snap)
                    body = prometheus_exposition(merged).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", PROMETHEUS_CONTENT_TYPE
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    try:
                        self.wfile.write(body)
                    except (BrokenPipeError, ConnectionResetError):
                        pass
                else:
                    self._reply(200, snap)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self) -> None:  # noqa: N802 — stdlib handler API
            trace_id = (
                sanitize_trace_id(self.headers.get(TRACE_HEADER))
                or new_trace_id()
            )
            if self.path != "/v1/act":
                self._reply(
                    404,
                    {"error": f"unknown path {self.path}"},
                    trace_id=trace_id,
                )
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length))
                obs = np.asarray(req["obs"], np.float32)
                deterministic = bool(req.get("deterministic", True))
                timeout_s = req.get("timeout_s")
                if timeout_s is not None:
                    timeout_s = float(timeout_s)
                slo_class = str(req.get("slo_class", "interactive"))
            except (ValueError, KeyError, TypeError) as e:
                self._reply(
                    400, {"error": f"bad request: {e}"}, trace_id=trace_id
                )
                return
            try:
                result = router.predict(
                    obs,
                    deterministic=deterministic,
                    timeout_s=timeout_s,
                    trace_id=trace_id,
                    slo_class=slo_class,
                )
            except BackpressureError as e:
                self._reply(
                    429,
                    {
                        "error": "backpressure",
                        "retry_after_s": e.retry_after_s,
                    },
                    retry_after_s=e.retry_after_s,
                    trace_id=trace_id,
                )
            except NoHealthyHosts as e:
                self._reply(503, {"error": str(e)}, trace_id=trace_id)
            except (RequestTimeout, TimeoutError, socket.timeout) as e:
                self._reply(
                    504,
                    {"error": f"deadline passed: {e}"},
                    trace_id=trace_id,
                )
            except ValueError as e:
                self._reply(
                    400, {"error": f"bad request: {e}"}, trace_id=trace_id
                )
            except Exception as e:  # noqa: BLE001 — no tracebacks on wire
                self._reply(
                    500, {"error": type(e).__name__}, trace_id=trace_id
                )
            else:
                self._reply(
                    200,
                    {
                        "actions": np.asarray(result.actions).tolist(),
                        "model_step": int(result.model_step),
                        "replica": int(result.replica),
                        "host": result.host,
                        "latency_s": round(result.latency_s, 6),
                    },
                    trace_id=trace_id,
                )

    return _Handler


class MeshFrontend(ThreadedHttpEndpoint):
    """Threaded HTTP door above a MetaRouter; ``port=0`` = ephemeral.
    Lifecycle (serve thread, shutdown ordering) shared with the RPC
    endpoint via :class:`~.rpc.ThreadedHttpEndpoint`."""

    thread_name = "mesh-frontend"

    def __init__(
        self,
        router: MetaRouter,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.router = router
        super().__init__(_make_handler(router), host, port)
