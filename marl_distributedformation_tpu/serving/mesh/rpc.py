"""Stdlib JSON-RPC plumbing for the mesh control plane.

The mesh tier (coordinator <-> host agents) needs exactly one transport
primitive: a blocking request/response call that either returns a JSON
payload or fails with a taxonomy the caller can act on. HTTP over
loopback already IS that primitive — the repo's serving frontend proved
the stdlib ``ThreadingHTTPServer`` handles it fine — so the control
plane reuses the same machinery instead of inventing a wire format:
``POST /rpc/{method}`` with a JSON body, JSON back.

Failure taxonomy (the whole point of having a wrapper):

- :class:`MeshUnreachable` — nobody answered: connection refused/reset,
  DNS, timeout. This is the *host-death signal* the coordinator's
  health logic and the MetaRouter's circuit breaker key on.
- :class:`MeshRpcError` — the peer answered with an error: unknown
  method (404) or a handler exception (500, carrying the exception type
  and a bounded detail string — no tracebacks over the wire, the
  frontend's discipline).

Everything here is host-side control-plane code. graftlint rule 21
(``rpc-in-traced-scope``) statically rejects any of these calls landing
inside a compiled scope — a socket round-trip under trace would fire
once per COMPILE and wedge the tracer on a dead peer.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

MAX_RPC_BODY_BYTES = 16 * 1024 * 1024  # gossip payloads are small dicts


class MeshRpcError(RuntimeError):
    """The peer answered with an error (bad method, handler raised)."""

    def __init__(
        self, method: str, detail: str, status: int = 500,
        error_type: str = "",
    ) -> None:
        super().__init__(f"rpc {method!r} failed ({status}): {detail}")
        self.method = method
        self.detail = detail
        self.status = status
        self.error_type = error_type


class MeshUnreachable(MeshRpcError):
    """Nobody answered: refused/reset/timeout — the host-death signal."""


def post_json(
    base_url: str,
    path: str,
    body: bytes,
    headers: Optional[Dict[str, str]] = None,
    timeout_s: float = 5.0,
):
    """One ``POST {base_url}{path}`` with a JSON body — the transport
    core shared by :func:`rpc_call`, the MetaRouter's ``/v1/act``
    forward, and ``ServingClient``'s endpoint mode (one place to fix
    connection handling, three callers). Returns ``(status,
    payload_dict, response_headers)``; an unparseable body degrades to
    ``{"error": <prefix>}``. Transport failures propagate raw
    (``OSError`` / ``http.client.HTTPException``) so each caller keeps
    its own failure taxonomy."""
    parsed = urllib.parse.urlsplit(base_url)
    conn = http.client.HTTPConnection(
        parsed.hostname, parsed.port, timeout=timeout_s
    )
    try:
        conn.request(
            "POST",
            path,
            body=body,
            headers={
                "Content-Type": "application/json",
                **(headers or {}),
            },
        )
        resp = conn.getresponse()
        raw = resp.read(MAX_RPC_BODY_BYTES)
        try:
            payload = json.loads(raw) if raw else {}
        except ValueError:
            payload = {"error": raw[:200].decode("utf-8", "replace")}
        return resp.status, payload, resp.headers
    finally:
        conn.close()


def rpc_call(
    base_url: str,
    method: str,
    payload: Optional[Dict[str, Any]] = None,
    timeout_s: float = 5.0,
) -> Dict[str, Any]:
    """One blocking ``POST {base_url}/rpc/{method}`` round trip.

    Returns the decoded JSON payload on 200; raises
    :class:`MeshUnreachable` when the transport fails and
    :class:`MeshRpcError` when the peer reports an error. Never used on
    the data path — the MetaRouter forwards ``/v1/act`` bodies itself —
    so a generous default timeout is fine."""
    parsed = urllib.parse.urlsplit(base_url)
    body = json.dumps(payload or {}).encode()
    conn = http.client.HTTPConnection(
        parsed.hostname, parsed.port, timeout=timeout_s
    )
    try:
        try:
            conn.request(
                "POST",
                f"/rpc/{method}",
                body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            raw = resp.read(MAX_RPC_BODY_BYTES)
        except (OSError, socket.timeout, http.client.HTTPException) as e:
            raise MeshUnreachable(
                method, f"{base_url} unreachable: {e!r}"
            ) from e
        try:
            data = json.loads(raw) if raw else {}
        except ValueError as e:
            raise MeshRpcError(
                method, f"unparseable response from {base_url}: {e}",
                status=resp.status,
            ) from e
        if resp.status != 200:
            raise MeshRpcError(
                method,
                str(data.get("error", raw[:200])),
                status=resp.status,
                error_type=str(data.get("error_type", "")),
            )
        return data
    finally:
        conn.close()


def _make_handler(handlers: Dict[str, Callable[[dict], dict]]):
    class _Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt: str, *args: Any) -> None:
            pass  # observability lives in the coordinator's registry

        def _reply(self, status: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass

        def do_POST(self) -> None:  # noqa: N802 — stdlib handler API
            if not self.path.startswith("/rpc/"):
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            method = self.path[len("/rpc/"):]
            handler = handlers.get(method)
            if handler is None:
                self._reply(
                    404,
                    {
                        "error": f"unknown rpc method {method!r}",
                        "methods": sorted(handlers),
                    },
                )
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                if not 0 <= length <= MAX_RPC_BODY_BYTES:
                    raise ValueError(
                        f"Content-Length must be in [0, {MAX_RPC_BODY_BYTES}]"
                    )
                payload = (
                    json.loads(self.rfile.read(length)) if length else {}
                )
            except (ValueError, TypeError) as e:
                self._reply(400, {"error": f"bad request: {e}"})
                return
            try:
                result = handler(payload)
            except Exception as e:  # noqa: BLE001 — typed over the wire
                self._reply(
                    500,
                    {
                        "error": repr(e)[:300],
                        "error_type": type(e).__name__,
                    },
                )
                return
            self._reply(200, result if result is not None else {})

    return _Handler


class ThreadedHttpEndpoint:
    """Shared lifecycle for the mesh tier's stdlib HTTP servers (this
    RPC endpoint and the MeshFrontend): one place owning the
    daemon-thread serve loop, ephemeral-port binding (``port=0`` —
    the bound port is ``self.port``), and shutdown ordering."""

    thread_name = "mesh-http"

    def __init__(
        self, handler_cls, host: str = "127.0.0.1", port: int = 0
    ) -> None:
        self.server = ThreadingHTTPServer((host, port), handler_cls)
        self.server.daemon_threads = True
        self.host = self.server.server_address[0]
        self.port = self.server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self.server.serve_forever,
            name=self.thread_name,
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self.server.shutdown()
        self.server.server_close()
        self._thread.join(timeout=10.0)
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


class JsonRpcServer(ThreadedHttpEndpoint):
    """Threaded RPC endpoint over a handler table. Handlers take the
    decoded payload dict and return a JSON-able dict; an exception
    becomes a typed 500 for the caller's :class:`MeshRpcError`."""

    thread_name = "mesh-rpc-server"

    def __init__(
        self,
        handlers: Dict[str, Callable[[dict], dict]],
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        super().__init__(_make_handler(dict(handlers)), host, port)
