"""Mesh smoke: the loopback 2-host acceptance storm (bench phase 14).

One call measures the four headline numbers the bench record commits:

- ``mesh_req_per_sec`` — client threads hammering the MetaRouter over
  both hosts for ``duration_s``;
- ``mesh_global_swap_latency_s_p50`` / ``_p95`` — wall time of
  coordinator-driven global reloads (prepare + commit across every
  host) under that load, measured over ``swaps`` ascending checkpoints;
- ``mesh_failover_lost_requests`` — accepted requests that never
  resolved (result or typed error) across a REAL ``kill -9`` of one
  host mid-load; the no-accepted-request-lost invariant demands 0;
- ``mesh_host_compile_receipts_max`` — the budget-1 receipt, per host,
  scraped from each surviving host's ``/v1/metrics``.

Also asserts the global monotonicity witness over every completed
response (mesh_step_violations must be 0 — the same checker the chaos
storm runs).
"""

from __future__ import annotations

import shutil
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from marl_distributedformation_tpu.serving.mesh.loopback import (
    spawn_local_mesh,
)
from marl_distributedformation_tpu.utils.checkpoint import (
    checkpoint_path,
    checkpoint_step,
    latest_checkpoint,
)


def make_checkpoint_series(
    log_dir: str | Path,
    promoted_dir: str | Path,
    num_agents: int = 3,
    num_formations: int = 4,
    iterations: int = 2,
) -> Tuple[Path, int]:
    """Train a tiny policy and publish its newest checkpoint into
    ``promoted_dir`` — the minimum a mesh needs to boot. Returns the
    promoted path and its step."""
    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    log_dir = Path(log_dir)
    promoted_dir = Path(promoted_dir)
    promoted_dir.mkdir(parents=True, exist_ok=True)
    env = EnvParams(num_agents=num_agents, max_steps=20)
    per_iter = num_formations * num_agents * 5
    Trainer(
        env,
        ppo=PPOConfig(n_steps=5, n_epochs=1, batch_size=32),
        config=TrainConfig(
            num_formations=num_formations,
            total_timesteps=iterations * per_iter,
            save_freq=1,
            name="mesh_smoke",
            log_dir=str(log_dir),
            seed=0,
        ),
    ).train()
    src = latest_checkpoint(log_dir)
    if src is None:
        raise RuntimeError(f"trainer left no checkpoint under {log_dir}")
    dst = promoted_dir / src.name
    shutil.copyfile(src, dst)
    return dst, checkpoint_step(dst)


def publish_next(
    promoted_dir: Path, src: Path, step: int
) -> Tuple[Path, int]:
    """Byte-copy ``src`` to an advanced step under the atomic-rename
    discipline — the storm's synthetic-candidate trick (exactly what a
    still-running trainer would provide)."""
    dst = checkpoint_path(promoted_dir, step)
    tmp = dst.with_name(f".{dst.name}.tmp")
    shutil.copyfile(src, tmp)
    tmp.replace(dst)
    return dst, step


class StepWitness:
    """Response-completion-order monotonicity recorder shared by the
    smoke's client threads (the chaos prober's ``steps`` shape)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.steps: List[Tuple[float, int]] = []
        self.ok = 0
        self.typed_errors = 0
        self.lost = 0

    def record(self, step: int) -> None:
        with self.lock:
            self.ok += 1
            self.steps.append((time.perf_counter(), int(step)))

    def violations(self) -> int:
        from marl_distributedformation_tpu.chaos import (
            check_step_monotonic,
        )

        with self.lock:
            return len(check_step_monotonic(self.steps))


def run_mesh_smoke(
    workdir: str | Path,
    hosts: int = 2,
    duration_s: float = 6.0,
    swaps: int = 3,
    clients: int = 4,
    num_agents: int = 3,
    buckets: Tuple[int, ...] = (1, 8),
    kill_host: bool = True,
    per_iter: int = 60,
    ready_timeout_s: float = 120.0,
) -> Dict[str, Any]:
    """The whole acceptance storm; returns the bench-field dict."""
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.serving.scheduler import (
        BackpressureError,
        RequestTimeout,
    )

    import numpy as np

    workdir = Path(workdir)
    promoted = workdir / "promoted"
    src, step0 = make_checkpoint_series(
        workdir / "train", promoted, num_agents=num_agents
    )
    env = EnvParams(num_agents=num_agents, max_steps=20)
    mesh = spawn_local_mesh(
        promoted,
        hosts=hosts,
        buckets=buckets,
        num_agents=num_agents,
        ready_timeout_s=ready_timeout_s,
        probe_interval_s=0.5,
    )
    witness = StepWitness()
    stop = threading.Event()
    obs = np.zeros((1, env.obs_dim), np.float32)

    def client_loop() -> None:
        from marl_distributedformation_tpu.serving.mesh.router import (
            NoHealthyHosts,
        )

        while not stop.is_set():
            try:
                result = mesh.router.predict(obs, timeout_s=5.0)
            except (
                BackpressureError,
                RequestTimeout,
                NoHealthyHosts,
                RuntimeError,
                OSError,
            ):
                with witness.lock:
                    witness.typed_errors += 1
                time.sleep(0.01)
                continue
            except BaseException:
                with witness.lock:
                    witness.lost += 1  # untyped = a lost request
                continue
            witness.record(result.model_step)

    threads = [
        threading.Thread(target=client_loop, daemon=True)
        for _ in range(clients)
    ]
    swap_latencies: List[float] = []
    killed: Optional[str] = None
    try:
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        # Load-phase swaps: ascending synthetic candidates committed
        # through the coordinator barrier while clients hammer.
        step = step0
        swap_every = duration_s / (swaps + 1)
        next_swap = t0 + swap_every
        kill_at = t0 + duration_s * 0.5
        while time.perf_counter() - t0 < duration_s:
            now = time.perf_counter()
            if kill_host and killed is None and now >= kill_at:
                killed = mesh.kill_host(0)
            if len(swap_latencies) < swaps and now >= next_swap:
                step += per_iter
                path, _ = publish_next(promoted, src, step)
                t_swap = time.perf_counter()
                if mesh.coordinator.global_reload(path):
                    swap_latencies.append(
                        time.perf_counter() - t_swap
                    )
                next_swap = now + swap_every
            time.sleep(0.02)
        elapsed = time.perf_counter() - t0
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15.0)
        receipts = mesh.router.host_compile_counts()
        mesh.stop()
    for t in threads:
        if t.is_alive():
            witness.lost += 1  # a thread wedged inside a request
    swap_latencies.sort()

    def pct(q: float) -> Optional[float]:
        if not swap_latencies:
            return None
        idx = min(len(swap_latencies) - 1, int(q * len(swap_latencies)))
        return round(swap_latencies[idx], 4)

    max_receipt = max(
        (c for per in receipts.values() for c in per.values()),
        default=0.0,
    )
    return {
        "mesh_hosts": hosts,
        "mesh_req_per_sec": round(witness.ok / max(elapsed, 1e-9), 1),
        "mesh_requests_ok": witness.ok,
        "mesh_typed_errors": witness.typed_errors,
        "mesh_failover_lost_requests": witness.lost,
        "mesh_step_violations": witness.violations(),
        "mesh_global_swaps": len(swap_latencies),
        "mesh_global_swap_latency_s_p50": pct(0.50),
        "mesh_global_swap_latency_s_p95": pct(0.95),
        "mesh_host_killed": killed,
        "mesh_commit_rounds": mesh.coordinator.commit_round,
        "mesh_final_step": mesh.coordinator.fleet_step,
        "mesh_host_compile_receipts_max": max_receipt,
        "mesh_host_compile_receipts": receipts,
    }
