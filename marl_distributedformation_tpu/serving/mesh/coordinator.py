"""MeshCoordinator: the control plane above per-host fleets.

The host tier's single source of truth (docs/mesh.md has the topology
diagram and the barrier state machine): a stdlib RPC service owning

- **the host registry** — hosts register ``(host_id, control_url,
  data_url, step)`` and renew a lease with every heartbeat; the
  heartbeat payload is the host's merged ``/v1/metrics`` namespace, so
  occupancy, queue depths, and p95s gossip upward with no extra
  endpoint (the MetaRouter routes off exactly this payload);
- **the health taxonomy** — a host that misses its lease turns
  ``suspect``; ``dead_after_s`` later it is ``dead`` (out of routing,
  out of barrier rounds) until a fresh heartbeat revives it. A revived
  or late-joining host whose served step is BEHIND the mesh step stays
  quarantined from routing until it catches up (the heartbeat reply
  carries the newest committed checkpoint path; the agent reloads
  locally and the next beat re-admits it) — "broken replicas still
  receive the new params" carried up a tier;
- **the cross-host reload barrier** — a two-phase generalization of the
  fleet's batch-barrier commit. ``global_reload`` drives PREPARE on
  every routable host (each host stages the checkpoint, closes its
  gates, and acquires every local replica barrier — it serves nothing
  while staged), and only when EVERY host acks does it drive COMMIT;
  any refusal, wedge, or timeout aborts the whole round and every host
  resumes on the old step. Because all hosts pause before any host
  commits, ``model_step`` stays globally monotonic in response
  completion order ACROSS hosts — the single-host invariant restated
  at the mesh tier. The pinned-reload exemption rides up unchanged:
  ``reload_pinned(..., monotonic=False)`` is the mesh-wide audited
  rollback.

The coordinator is duck-type-compatible with ``FleetReloadCoordinator``
where the pipeline supervisor touches it (``log_dir`` / ``refresh`` /
``fleet_step`` / ``reload_pinned`` / ``swap_count`` / ``load_errors`` /
``last_commit``), so ``AlwaysLearningPipeline.attach_fleet`` promotes
the always-learning loop to the mesh with zero supervisor changes: the
Promoter publishes ONCE into ``promoted/``, and this coordinator drives
the global commit.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Deque, Dict, List, Optional, Tuple

from marl_distributedformation_tpu.chaos.plane import fault_point
from marl_distributedformation_tpu.obs import get_registry, get_tracer
from marl_distributedformation_tpu.serving.mesh.rpc import (
    JsonRpcServer,
    MeshRpcError,
    MeshUnreachable,
    rpc_call,
)
from marl_distributedformation_tpu.utils.checkpoint import (
    CheckpointDiscovery,
    checkpoint_step,
)

HOST_ALIVE = "alive"
HOST_SUSPECT = "suspect"
HOST_DEAD = "dead"


@dataclasses.dataclass
class MeshHost:
    """One registered host's control-plane state."""

    host_id: str
    control_url: str
    data_url: str
    # Every mutable field below is owned by the coordinator's registry
    # lock: heartbeats, sweeps, out-of-band death verdicts, and commit
    # legs all mutate through ``MeshCoordinator._hosts_lock``.
    step: int  # graftlock: guarded-by=_hosts_lock — newest KNOWN served step
    last_beat: float  # graftlock: guarded-by=_hosts_lock — monotonic
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)  # graftlock: guarded-by=_hosts_lock
    beats: int = 0  # graftlock: guarded-by=_hosts_lock
    forced_dead: bool = False  # graftlock: guarded-by=_hosts_lock — out-of-band
    # death verdict (barrier RPC unreachable); a fresh heartbeat clears it
    dead_reason: str = ""  # graftlock: guarded-by=_hosts_lock
    committed_round: int = -1  # graftlock: guarded-by=_hosts_lock — last acked commit round

    def record(self, state: str) -> dict:
        return {
            "host_id": self.host_id,
            "control_url": self.control_url,
            "data_url": self.data_url,
            "step": int(self.step),
            "state": state,
            "beats": int(self.beats),
            "dead_reason": self.dead_reason,
        }


class MeshCoordinator:
    """Host registry + gossip + the coordinator-barriered global reload.

    Args:
      log_dir: the ``promoted/`` directory whose newest checkpoint the
        mesh should serve (``refresh`` polls it once for the WHOLE
        mesh — the fleet coordinator's poll-once discipline, one tier
        up). ``None`` disables discovery (``global_reload`` by explicit
        path still works).
      lease_s: heartbeat lease; a host silent past it is ``suspect``.
      dead_after_s: additional silence before ``suspect`` becomes
        ``dead`` (out of routing and barrier rounds).
      prepare_timeout_s: per-host bound on the PREPARE RPC — a host
        wedged mid-stage aborts the round (every host restored) instead
        of pausing the mesh forever.
      commit_timeout_s: per-host bound on the COMMIT RPC; an
        unreachable host at commit time is marked dead (it serves
        nothing), the round still lands on the others.
      host/port: the RPC bind address (``port=0`` = ephemeral).
      model_id: optional tenant lane (serving/tenancy) this
        coordinator's watched directory promotes — stamped into
        ``last_commit`` so the promotion log's mesh attribution
        (schema 5) names the lane a global swap landed for.
    """

    def __init__(
        self,
        log_dir: Optional[str | Path] = None,
        lease_s: float = 2.0,
        dead_after_s: float = 4.0,
        prepare_timeout_s: float = 30.0,
        commit_timeout_s: float = 10.0,
        prepare_ttl_s: float = 60.0,
        poll_interval_s: float = 2.0,
        max_recorded_errors: int = 32,
        host: str = "127.0.0.1",
        port: int = 0,
        model_id: Optional[str] = None,
    ) -> None:
        self.log_dir = Path(log_dir) if log_dir is not None else None
        self.model_id = model_id
        self.lease_s = float(lease_s)
        self.dead_after_s = float(dead_after_s)
        self.prepare_timeout_s = float(prepare_timeout_s)
        self.commit_timeout_s = float(commit_timeout_s)
        # Host-side orphan bound, advertised with every PREPARE: must
        # outlive a live coordinator's whole round so it only ever
        # fires when the coordinator itself died mid-round.
        self.prepare_ttl_s = float(prepare_ttl_s)
        self.poll_interval_s = float(poll_interval_s)
        self.swap_count = 0  # graftlock: guarded-by=_refresh_lock
        self.commit_round = 0  # graftlock: guarded-by=_refresh_lock
        self.last_commit: Optional[dict] = None  # graftlock: guarded-by=_refresh_lock
        self.last_commit_path: Optional[str] = None  # graftlock: guarded-by=_refresh_lock
        # Unannotated on purpose: deque.append is atomic under the GIL
        # and the watch thread records poll failures without a lock.
        self.load_errors: Deque[Tuple[str, str]] = deque(
            maxlen=max_recorded_errors
        )
        self._mesh_step = -1  # graftlock: guarded-by=_hosts_lock
        self._hosts: Dict[str, MeshHost] = {}  # graftlock: guarded-by=_hosts_lock
        # Held on EVERY heartbeat/register RPC; any blocking work under
        # it stalls the whole gossip plane — hence the gate marking.
        self._hosts_lock = threading.Lock()  # graftlock: gate
        self._refresh_lock = threading.Lock()
        self._discovery = (
            CheckpointDiscovery(self.log_dir)
            if self.log_dir is not None
            else None
        )
        self._server = JsonRpcServer(
            {
                "mesh.register": self._rpc_register,
                "mesh.heartbeat": self._rpc_heartbeat,
                "mesh.deregister": self._rpc_deregister,
                "mesh.hosts": self._rpc_hosts,
            },
            host=host,
            port=port,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    @property
    def url(self) -> str:
        return self._server.url

    def start(self) -> "MeshCoordinator":
        """Serve the RPC endpoint and run the background watcher
        (directory poll + health sweep)."""
        self._server.start()
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch, name="mesh-coordinator", daemon=True
            )
            self._thread.start()
        return self

    def serve(self) -> "MeshCoordinator":
        """RPC endpoint only — no background poll (tests and callers
        that drive ``refresh()`` explicitly)."""
        self._server.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self._server.stop()

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.sweep()
                self.refresh()
            except Exception as e:  # noqa: BLE001 — the control plane
                # must outlive a transient poll failure
                self.load_errors.append(("<watch>", repr(e)))

    def __enter__(self) -> "MeshCoordinator":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- registry + gossip (RPC handlers) --------------------------------

    def _rpc_register(self, payload: dict) -> dict:
        host_id = str(payload["host_id"])
        with self._hosts_lock:
            self._hosts[host_id] = MeshHost(
                host_id=host_id,
                control_url=str(payload["control_url"]),
                data_url=str(payload["data_url"]),
                step=int(payload.get("step", -1)),
                last_beat=time.monotonic(),
            )
            # A mesh bootstrapping from already-serving hosts adopts
            # the newest step any of them serves (the fleet
            # coordinator's seeding rule, one tier up).
            if self._mesh_step < 0:
                self._mesh_step = max(
                    h.step for h in self._hosts.values()
                )
        get_registry().counter("mesh_registrations_total").inc()
        return self._beat_reply()

    def _rpc_heartbeat(self, payload: dict) -> dict:
        fault_point("mesh.heartbeat")
        host_id = str(payload["host_id"])
        with self._hosts_lock:
            h = self._hosts.get(host_id)
            if h is None:
                # Coordinator restarted (or the host was pruned): tell
                # the agent to re-register rather than silently gossip
                # into the void.
                return {"registered": False}
            h.last_beat = time.monotonic()
            h.beats += 1
            if h.forced_dead:
                h.forced_dead = False
                h.dead_reason = ""
            if "step" in payload:
                beat_step = int(payload["step"])
                if (
                    h.committed_round == self.commit_round
                    and beat_step != h.step
                ):
                    # A beat sent BEFORE this round's commit landed on
                    # the host but processed after the commit leg
                    # recorded its step — the host provably installed
                    # this round's step (it acked the commit) and only
                    # the coordinator moves steps, so a disagreeing
                    # beat is stale; honoring it would transiently
                    # quarantine a freshly-committed host.
                    pass
                else:
                    h.step = beat_step
            metrics = payload.get("metrics")
            if isinstance(metrics, dict):
                h.metrics = metrics
        return self._beat_reply()

    def _rpc_deregister(self, payload: dict) -> dict:
        with self._hosts_lock:
            self._hosts.pop(str(payload.get("host_id", "")), None)
        return {"ok": True}

    def _rpc_hosts(self, payload: dict) -> dict:
        return {"hosts": self.hosts()}

    def _beat_reply(self) -> dict:
        """What every register/heartbeat response carries: the lease
        terms plus the mesh's serving target, so a stale host learns it
        must catch up (``mesh_path`` is the checkpoint to reload)."""
        return {
            "registered": True,
            "lease_s": self.lease_s,
            "mesh_step": int(self._mesh_step),
            "mesh_path": self.last_commit_path,
            "commit_round": int(self.commit_round),
        }

    # -- health ----------------------------------------------------------

    def _state(self, h: MeshHost, now: float) -> str:
        if h.forced_dead:
            return HOST_DEAD
        silence = now - h.last_beat
        if silence <= self.lease_s:
            return HOST_ALIVE
        if silence <= self.lease_s + self.dead_after_s:
            return HOST_SUSPECT
        return HOST_DEAD

    def hosts(self) -> List[dict]:
        """Registry snapshot with the computed health state."""
        now = time.monotonic()
        with self._hosts_lock:
            return [
                h.record(self._state(h, now))
                for h in self._hosts.values()
            ]

    def routable_hosts(self) -> List[MeshHost]:
        """Hosts the MetaRouter may send traffic to: not dead AND
        serving EXACTLY the mesh step. A host behind (revived/late,
        missed a commit) OR ahead (a lost-ack commit the round never
        counted) is quarantined — either skew, routed next to an
        at-step peer, interleaves different ``model_step``s in
        response completion order, the exact violation the barrier
        exists to prevent. Behind-hosts catch up via the heartbeat's
        advertised path; ahead-hosts re-admit when the next refresh
        round counts them (``already_at_step``) and advances the mesh
        step."""
        now = time.monotonic()
        with self._hosts_lock:
            return [
                h
                for h in self._hosts.values()
                if self._state(h, now) != HOST_DEAD
                and (self._mesh_step < 0 or h.step == self._mesh_step)
            ]

    def barrier_hosts(self) -> List[MeshHost]:
        """Hosts a reload round must include: every not-dead host,
        stale ones too (the round is exactly how they advance)."""
        now = time.monotonic()
        with self._hosts_lock:
            return [
                h
                for h in self._hosts.values()
                if self._state(h, now) != HOST_DEAD
            ]

    def sweep(self) -> None:
        """Record health transitions (counters + incident on a death).
        State is computed from timestamps on every read, so the sweep
        only exists to make transitions OBSERVABLE, not to make them
        happen."""
        now = time.monotonic()
        alive = suspect = dead = 0
        died: List[Tuple[str, float]] = []
        with self._hosts_lock:
            total = len(self._hosts)
            for h in self._hosts.values():
                state = self._state(h, now)
                if state == HOST_ALIVE:
                    alive += 1
                elif state == HOST_SUSPECT:
                    suspect += 1
                else:
                    dead += 1
                    if not h.dead_reason:
                        # The verdict write stays under the registry
                        # lock — heartbeats clear dead_reason and
                        # mark_dead sets it, both under _hosts_lock.
                        h.dead_reason = (
                            f"lease expired {now - h.last_beat:.2f}s ago"
                        )
                        died.append((h.host_id, now - h.last_beat))
        # Counters and the incident dump run AFTER release: the tracer's
        # ring lock must never nest under the heartbeat dispatch lock.
        registry = get_registry()
        for host_id, silence_s in died:
            registry.counter("mesh_host_deaths_total").inc()
            get_tracer().incident(
                "mesh_host_dead",
                host_id=host_id,
                silence_s=round(silence_s, 3),
            )
        registry.gauge("mesh_hosts").set(total)
        registry.gauge("mesh_hosts_alive").set(alive)
        registry.gauge("mesh_hosts_suspect").set(suspect)
        registry.gauge("mesh_hosts_dead").set(dead)

    def mark_dead(self, host_id: str, reason: str) -> None:
        """Out-of-band death verdict (an unreachable barrier RPC, the
        MetaRouter's circuit breaker). A fresh heartbeat revives."""
        with self._hosts_lock:
            h = self._hosts.get(host_id)
            if h is None or h.forced_dead:
                return
            h.forced_dead = True
            h.dead_reason = reason
        get_registry().counter("mesh_host_deaths_total").inc()
        get_tracer().incident(
            "mesh_host_dead", host_id=host_id, reason=reason
        )

    # -- the cross-host reload barrier -----------------------------------

    @property
    def fleet_step(self) -> int:
        """The step every post-commit response carries, mesh-wide (the
        FleetReloadCoordinator-compatible name the supervisor reads)."""
        return self._mesh_step

    def refresh(self, trace_id: Optional[str] = None) -> bool:
        """Poll the promoted directory ONCE for the whole mesh;
        global-reload if a newer checkpoint landed."""
        if self._discovery is None:
            return False
        with self._refresh_lock:
            path = self._discovery.latest()
            if path is None:
                return False
            step = checkpoint_step(path)
            if step <= self._mesh_step:
                return False
            return self._global_reload_locked(
                path, step, monotonic=True, trace_id=trace_id
            )

    def reload_pinned(
        self,
        path: str | Path,
        monotonic: bool = True,
        trace_id: Optional[str] = None,
    ) -> bool:
        """Mesh-wide pinned swap; ``monotonic=False`` is the audited
        rollback exemption carried up from the fleet tier — same
        containment contract (failures recorded, old step serves)."""
        path = Path(path)
        with self._refresh_lock:
            try:
                step = checkpoint_step(path)
            except ValueError as e:
                self.load_errors.append((str(path), repr(e)))
                return False
            if monotonic and step <= self._mesh_step:
                return False
            if step == self._mesh_step:
                return False
            return self._global_reload_locked(
                path, step, monotonic=monotonic, trace_id=trace_id
            )

    def global_reload(
        self,
        path: str | Path,
        monotonic: bool = True,
        trace_id: Optional[str] = None,
    ) -> bool:
        """Explicit-path global swap (the CLI / smoke entry)."""
        return self.reload_pinned(path, monotonic=monotonic, trace_id=trace_id)

    # graftlock: holds=_refresh_lock
    def _global_reload_locked(
        self,
        path: Path,
        step: int,
        monotonic: bool,
        trace_id: Optional[str],
    ) -> bool:
        """Two-phase commit over every barrier-eligible host. Caller
        holds ``_refresh_lock``."""
        hosts = self.barrier_hosts()
        if not hosts:
            self.load_errors.append(
                (str(path), "no live hosts to commit to")
            )
            return False
        tracer = get_tracer()
        registry = get_registry()
        self.commit_round += 1
        round_id = self.commit_round
        t0 = time.perf_counter()
        staged: List[MeshHost] = []
        already: List[MeshHost] = []
        abort_reason = ""
        with tracer.span(
            "mesh.prepare", trace_id=trace_id, step=step, round=round_id,
            hosts=len(hosts),
        ):
            for h in hosts:
                try:
                    fault_point("mesh.rpc")
                    resp = rpc_call(
                        h.control_url,
                        "mesh.prepare",
                        {
                            "round": round_id,
                            "path": str(path),
                            "step": step,
                            "monotonic": monotonic,
                            "trace_id": trace_id,
                            "ttl_s": self.prepare_ttl_s,
                        },
                        timeout_s=self.prepare_timeout_s,
                    )
                except MeshUnreachable as e:
                    # SAFETY over progress: a host we cannot reach may
                    # still be serving the old step — committing the
                    # others would let its in-flight old-step responses
                    # complete after new-step ones. Abort the round;
                    # the health plane (missed leases) owns declaring
                    # it dead, after which the retry round proceeds
                    # without it.
                    abort_reason = (
                        f"host {h.host_id} unreachable at prepare: {e}"
                    )
                    break
                except MeshRpcError as e:
                    abort_reason = (
                        f"host {h.host_id} prepare failed: {e}"
                    )
                    break
                except Exception as e:  # noqa: BLE001 — injected fault
                    # (chaos plane) or a coordinator-side bug: same
                    # abort path, the control plane must not die.
                    abort_reason = f"prepare leg failed: {e!r}"
                    break
                if resp.get("already_at_step"):
                    # The host already serves this step (a commit ack
                    # lost to a timeout, a catch-up that won the race):
                    # nothing to stage or pause — count it committed.
                    already.append(h)
                    continue
                if not resp.get("staged"):
                    abort_reason = (
                        f"host {h.host_id} refused prepare: "
                        f"{resp.get('reason', 'unknown')}"
                    )
                    break
                staged.append(h)
        if abort_reason:
            # Best-effort abort to EVERY round participant, not just
            # the acked ones: a host whose prepare wedged past our
            # timeout may stage AFTER this abort round-trips — the
            # next round's refused-prepare -> abort (and the host-side
            # TTL) are the backstops that release it.
            for h in hosts:
                try:
                    rpc_call(
                        h.control_url,
                        "mesh.abort",
                        {"round": round_id, "reason": abort_reason},
                        timeout_s=self.commit_timeout_s,
                    )
                except MeshRpcError:
                    pass  # its prepare TTL is the backstop
            self.load_errors.append(
                (
                    str(path),
                    f"round {round_id} aborted: {abort_reason}; every "
                    "host restored, old step keeps serving mesh-wide",
                )
            )
            registry.counter("mesh_reload_aborts_total").inc()
            tracer.incident(
                "mesh_barrier_abort",
                trace_id=trace_id,
                round=round_id,
                step=step,
                reason=abort_reason,
                staged_hosts=[h.host_id for h in staged],
            )
            return False
        committed = 0
        with tracer.span(
            "mesh.commit", trace_id=trace_id, step=step, round=round_id,
        ):
            for h in staged:
                # The commit leg is the one place a transient failure
                # would leave a host staged-and-paused with requests
                # parked behind its gates — retried, because a parked
                # request resuming on the OLD step after others served
                # the new one is the exact violation this barrier
                # exists to prevent. A host UNREACHABLE through every
                # retry is presumed dead: staged means paused, so it
                # serves nothing until its prepare TTL aborts it, and
                # its stale step then keeps it out of routing until
                # catch-up.
                ok = False
                for commit_try in range(3):
                    try:
                        fault_point("mesh.rpc")
                        resp = rpc_call(
                            h.control_url,
                            "mesh.commit",
                            {"round": round_id, "trace_id": trace_id},
                            timeout_s=self.commit_timeout_s,
                        )
                        ok = bool(resp.get("ok"))
                        break
                    except MeshUnreachable as e:
                        if commit_try == 2:
                            self.mark_dead(
                                h.host_id,
                                f"unreachable at commit: {e}",
                            )
                    except Exception:  # noqa: BLE001 — injected
                        # fault (chaos) or a coordinator-side bug on
                        # this leg: retry; the host-side handler is
                        # idempotent per round.
                        pass
                if ok:
                    committed += 1
                    with self._hosts_lock:
                        h.step = step
                        h.committed_round = round_id
        for h in already:
            committed += 1
            with self._hosts_lock:
                h.step = step
                h.committed_round = round_id
        if committed == 0:
            self.load_errors.append(
                (
                    str(path),
                    f"round {round_id}: no host committed; old step "
                    "keeps serving",
                )
            )
            registry.counter("mesh_reload_aborts_total").inc()
            return False
        with self._hosts_lock:
            # The mesh step is the heartbeat/quarantine comparison point
            # (read by _beat_reply and routable_hosts under _hosts_lock)
            # — advancing it under only _refresh_lock let a concurrent
            # beat observe the new step before the host records did.
            self._mesh_step = step
        self.swap_count += 1
        self.last_commit_path = str(path)
        self.last_commit = {
            "commit_round": round_id,
            "host_count": committed,
            "step": step,
        }
        if self.model_id is not None:
            self.last_commit["model_id"] = self.model_id
        swap_s = time.perf_counter() - t0
        registry.counter("mesh_global_swaps_total").inc()
        registry.gauge("mesh_step").set(step)
        registry.histogram("mesh_global_swap_seconds").observe(swap_s)
        return True
