"""Mesh host process: one fleet + frontend + agent, loopback-spawnable.

``python -m marl_distributedformation_tpu.serving.mesh.host`` boots the
full per-host serving stack — ``FleetRouter`` over the local devices,
``FleetFrontend`` on the data port, ``HostAgent`` on the control port —
from a promoted-checkpoint directory, registers with the coordinator,
and serves until killed. This is the unit the loopback mesh
(``serving/mesh/loopback.py``), the chaos storm's ``--mesh`` campaign,
and bench phase 14 spawn as real OS processes: ``kill -9`` of one of
these is a REAL host death, not a ``SimulatedCrash``.

The process prints exactly ONE JSON line on stdout when ready::

    {"ready": true, "host_id": ..., "data_url": ..., "control_url": ...,
     "pid": ..., "step": ...}

and nothing else (logs go to stderr), so a parent can parse the ports
it bound ephemerally. ``--fault-spec`` arms the process-local chaos
plane with an explicit JSON fault list — how the wedged-host barrier
tests make THIS host (and only this host) misbehave deterministically.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from typing import List, Optional


def _force_cpu_devices(n: int) -> None:
    """The serve_policy/conftest dance: land the virtual-device flag
    and honor JAX_PLATFORMS even under this image's sitecustomize
    (which imports jax at interpreter start and swallows the env
    var)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if jax.default_backend() != "cpu" or len(jax.local_devices()) >= n:
        return
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except (AttributeError, RuntimeError):
        try:
            import jax.extend.backend as jeb

            jeb.clear_backends()
        except Exception:  # noqa: BLE001 — widening is best-effort
            pass


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--promoted-dir", required=True,
        help="coordinator-watched checkpoint directory to serve from",
    )
    ap.add_argument("--coordinator-url", required=True)
    ap.add_argument("--host-id", required=True)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--buckets", default="1,8")
    ap.add_argument("--obs-dim", type=int, default=None)
    ap.add_argument("--act-dim", type=int, default=2)
    ap.add_argument(
        "--num-agents", type=int, default=None,
        help="build EnvParams(num_agents=...) for per-formation "
        "policies (obs-dim then derives from it)",
    )
    ap.add_argument("--port", type=int, default=0, help="data port")
    ap.add_argument("--control-port", type=int, default=0)
    ap.add_argument("--heartbeat-s", type=float, default=0.25)
    ap.add_argument("--window-ms", type=float, default=2.0)
    ap.add_argument(
        "--fault-spec", default=None,
        help="JSON list of {point, kind, at_hit, seconds} to arm on "
        "THIS host's chaos plane (deterministic misbehavior for the "
        "barrier tests)",
    )
    args = ap.parse_args(argv)

    _force_cpu_devices(max(1, args.replicas))

    from marl_distributedformation_tpu.serving.fleet import (
        FleetFrontend,
        fleet_from_checkpoint_dir,
        warmup_fleet,
    )
    from marl_distributedformation_tpu.serving.mesh.agent import HostAgent

    env_params = None
    obs_dim = args.obs_dim
    if args.num_agents is not None:
        from marl_distributedformation_tpu.env import EnvParams

        env_params = EnvParams(num_agents=args.num_agents)
        obs_dim = env_params.obs_dim
    if obs_dim is None:
        ap.error("--obs-dim or --num-agents is required (warmup shape)")

    if args.fault_spec:
        from marl_distributedformation_tpu.chaos import (
            FaultSchedule,
            FaultSpec,
            get_fault_plane,
        )

        specs = [
            FaultSpec(
                point=str(s["point"]),
                kind=str(s["kind"]),
                at_hit=int(s.get("at_hit", 1)),
                seconds=float(s.get("seconds", 0.0)),
            )
            for s in json.loads(args.fault_spec)
        ]
        plane = get_fault_plane()
        plane.arm(FaultSchedule(specs))
        plane.enabled = True
        print(
            f"[mesh-host {args.host_id}] chaos armed: {len(specs)} "
            "fault(s)",
            file=sys.stderr,
        )

    router, fleet = fleet_from_checkpoint_dir(
        args.promoted_dir,
        env_params=env_params,
        act_dim=args.act_dim,
        num_replicas=args.replicas,
        buckets=tuple(int(b) for b in args.buckets.split(",") if b),
        window_ms=args.window_ms,
    )
    # The MESH coordinator drives every reload through the agent's
    # staged two-phase RPCs — the local directory watcher must stay
    # off, or host-local polls would race the global barrier.
    router.start()
    warmup_fleet(router, (obs_dim,))
    frontend = FleetFrontend(router, port=args.port).start()
    agent = HostAgent(
        host_id=args.host_id,
        router=router,
        fleet=fleet,
        coordinator_url=args.coordinator_url,
        data_url=frontend.url,
        control_port=args.control_port,
        heartbeat_interval_s=args.heartbeat_s,
    ).start()

    print(
        json.dumps(
            {
                "ready": True,
                "host_id": args.host_id,
                "data_url": frontend.url,
                "control_url": agent.control_url,
                "pid": os.getpid(),
                "step": int(fleet.fleet_step),
            }
        ),
        flush=True,
    )

    done = threading.Event()

    def _term(signum, frame) -> None:
        done.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        done.wait()
    finally:
        agent.stop()
        frontend.stop()
        router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
