"""Traffic-replay load generator: drive the fleet at production-like
load, measure req/s AT a latency target.

The smoke storms (smoke.py, fleet/smoke.py) are CLOSED-loop: each client
waits for its response before sending the next request, so measured
throughput self-limits to whatever the server sustains and the latency
tail never sees overload. Production traffic is OPEN-loop — arrivals
don't care how busy the server is — and the number capacity planning
needs is "max sustained request rate while p95 stays under the SLO",
not peak closed-loop req/s (the Podracer/JaxMARL throughput discipline,
applied to the serving side: report the rate you can HOLD, not the rate
you once touched).

This module provides:

- :class:`RequestTrace` — a replayable request stream: inter-arrival
  gaps, request sizes, SLO classes. Synthesize one from distributions
  (:func:`synthetic_trace`) or record/replay real traffic as JSONL
  (:func:`save_trace` / :func:`load_trace`). Traces are deterministic
  given a seed — the ladder autotuner (autotune.py) consumes the same
  trace the bench drives, so its decisions are reproducible.
- :class:`TraceRecorder` — a bounded ring the schedulers record LIVE
  arrivals into; its window replays through the same autotuner DP
  (serving/elastic) and dumps as the same JSONL
  (``serve_policy.py --record-trace``).
- :func:`run_load` — open-loop replay of a trace against anything with
  ``submit`` (scheduler or router): arrivals are scheduled on the trace
  clock regardless of completions; rejects/timeouts are counted, not
  retried (a retry storm would hide the overload the measurement
  exists to see).
- :func:`max_rate_at_slo` — bisection over offered rate: the highest
  rate whose replay holds ``p95 <= target`` with at most ``max_loss``
  of requests rejected/timed out. This is bench phase 9's
  ``serving_req_per_sec_at_p95_slo``.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    RequestTimeout,
)

# Size mix loosely shaped like interactive inference traffic: mostly
# single-row lookups, a tail of batched callers reaching into the big
# rungs. Weights are the knob — record a real trace when you have one.
DEFAULT_SIZE_MIX: Tuple[Tuple[int, float], ...] = (
    (1, 0.50),
    (4, 0.20),
    (16, 0.12),
    (64, 0.10),
    (256, 0.08),
)


@dataclasses.dataclass(frozen=True)
class RequestTrace:
    """A replayable request stream. ``inter_arrival_s[i]`` is the gap
    before request ``i``; ``sizes[i]`` its row count; ``slo_classes[i]``
    its admission class ("interactive"/"batch")."""

    inter_arrival_s: np.ndarray
    sizes: np.ndarray
    slo_classes: Tuple[str, ...]

    def __post_init__(self) -> None:
        n = len(self.sizes)
        if not (len(self.inter_arrival_s) == n == len(self.slo_classes)):
            raise ValueError(
                f"trace arrays disagree on length: {n} sizes, "
                f"{len(self.inter_arrival_s)} gaps, "
                f"{len(self.slo_classes)} classes"
            )

    def __len__(self) -> int:
        return len(self.sizes)

    @property
    def duration_s(self) -> float:
        return float(np.sum(self.inter_arrival_s))

    @property
    def offered_rps(self) -> float:
        d = self.duration_s
        return len(self) / d if d > 0 else 0.0

    def scaled_to_rate(self, rate_rps: float) -> "RequestTrace":
        """Same request sequence replayed at a different offered rate
        (gaps scaled uniformly) — how the SLO search sweeps rate
        without changing the size/class mix."""
        if rate_rps <= 0:
            raise ValueError(f"rate must be positive, got {rate_rps}")
        factor = self.offered_rps / rate_rps
        return dataclasses.replace(
            self, inter_arrival_s=self.inter_arrival_s * factor
        )


def synthetic_trace(
    duration_s: float,
    rate_rps: float,
    seed: int = 0,
    size_mix: Sequence[Tuple[int, float]] = DEFAULT_SIZE_MIX,
    batch_fraction: float = 0.0,
) -> RequestTrace:
    """Poisson arrivals at ``rate_rps`` for ``duration_s`` with sizes
    drawn from ``size_mix`` (``(rows, weight)`` pairs) and a
    ``batch_fraction`` share of batch-class requests. Deterministic in
    ``seed``."""
    rng = np.random.default_rng(seed)
    n = max(1, int(round(duration_s * rate_rps)))
    gaps = rng.exponential(1.0 / rate_rps, size=n)
    sizes_v = np.array([s for s, _ in size_mix], dtype=np.int64)
    weights = np.array([w for _, w in size_mix], dtype=np.float64)
    weights = weights / weights.sum()
    sizes = rng.choice(sizes_v, size=n, p=weights)
    classes = tuple(
        "batch" if rng.random() < batch_fraction else "interactive"
        for _ in range(n)
    )
    return RequestTrace(
        inter_arrival_s=gaps.astype(np.float64),
        sizes=sizes,
        slo_classes=classes,
    )


def save_trace(trace: RequestTrace, path: str | Path) -> None:
    """One JSONL line per request: ``{"dt": gap_s, "n": rows,
    "slo": class}`` — the recordable interchange format."""
    with open(path, "w") as f:
        for dt, n, slo in zip(
            trace.inter_arrival_s, trace.sizes, trace.slo_classes
        ):
            f.write(
                json.dumps({"dt": float(dt), "n": int(n), "slo": slo})
                + "\n"
            )


def load_trace(path: str | Path) -> RequestTrace:
    gaps: List[float] = []
    sizes: List[int] = []
    classes: List[str] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            gaps.append(float(rec["dt"]))
            sizes.append(int(rec["n"]))
            classes.append(str(rec.get("slo", "interactive")))
    if not sizes:
        raise ValueError(f"empty request trace: {path}")
    return RequestTrace(
        inter_arrival_s=np.asarray(gaps, np.float64),
        sizes=np.asarray(sizes, np.int64),
        slo_classes=tuple(classes),
    )


class TraceRecorder:
    """Bounded ring of LIVE arrivals, replayable as a
    :class:`RequestTrace`.

    The schedulers record every offered request (rows + SLO class,
    stamped at admission time) into one shared recorder; the elastic
    controller (serving/elastic) replays the recent window through the
    autotuner's exact DP, and ``serve_policy.py --record-trace`` dumps
    it as the same JSONL :func:`load_trace` reads back — closing the
    synthetic-only gap: the trace that retunes the fleet is the trace
    the fleet actually served.

    OFFERED load is what gets recorded — the sample lands before
    admission control, so backpressured requests still count (a retuner
    fed only the accepted stream would never see the overload it exists
    to fix). The ring is bounded (``capacity`` newest arrivals) and the
    record path is one lock + one deque append — cheap enough for the
    submit path.
    """

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 2:
            raise ValueError(
                f"capacity must allow at least one gap, got {capacity}"
            )
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # (perf_counter arrival, rows, slo_class) newest-last.
        self._ring: "deque" = deque(maxlen=self.capacity)  # graftlock: guarded-by=_lock
        self._recorded_total = 0  # graftlock: guarded-by=_lock

    def record(
        self, rows: int, slo_class: str = "interactive"
    ) -> None:
        """One offered request (called by the schedulers at submit)."""
        now = time.perf_counter()
        with self._lock:
            self._ring.append((now, int(rows), str(slo_class)))
            self._recorded_total += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def recorded_total(self) -> int:
        """Arrivals ever recorded (the ring keeps only the newest)."""
        with self._lock:
            return self._recorded_total

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def to_trace(self) -> Optional[RequestTrace]:
        """The ring as a replayable trace (None below two samples —
        one arrival has no gap to replay). The first gap is 0: the
        window starts at its own first arrival."""
        with self._lock:
            samples = list(self._ring)
        if len(samples) < 2:
            return None
        times = np.asarray([t for t, _, _ in samples], np.float64)
        gaps = np.diff(times, prepend=times[0])
        return RequestTrace(
            inter_arrival_s=gaps,
            sizes=np.asarray([n for _, n, _ in samples], np.int64),
            slo_classes=tuple(slo for _, _, slo in samples),
        )

    def save(self, path: str | Path) -> bool:
        """Dump the ring as replayable loadgen JSONL; False when there
        is not yet enough recorded traffic to form a trace."""
        trace = self.to_trace()
        if trace is None:
            return False
        save_trace(trace, path)
        return True


@dataclasses.dataclass
class LoadReport:
    """What one open-loop replay measured. ``per_size_p95_ms`` keys the
    p95 by request row count — how the sharded-vs-replicated bench
    isolates the big-rung latency from the mixed stream."""

    offered_rps: float
    duration_s: float
    submitted: int
    ok: int
    rejected: int
    timed_out: int
    failed: int
    p50_ms: float
    p95_ms: float
    p99_ms: float
    per_size_p95_ms: Dict[int, float] = dataclasses.field(
        default_factory=dict
    )

    @property
    def loss_fraction(self) -> float:
        bad = self.rejected + self.timed_out + self.failed
        return bad / self.submitted if self.submitted else 1.0

    def meets(self, p95_target_ms: float, max_loss: float) -> bool:
        """Did this replay hold the SLO? Requires completed traffic —
        an all-rejected replay has a vacuous p95."""
        return (
            self.ok > 0
            and self.p95_ms <= p95_target_ms
            and self.loss_fraction <= max_loss
        )

    def to_dict(self) -> Dict[str, object]:
        out = dataclasses.asdict(self)
        per_size = out.pop("per_size_p95_ms")
        out = {k: round(float(v), 6) for k, v in out.items()}
        out["loss_fraction"] = round(self.loss_fraction, 6)
        out["per_size_p95_ms"] = {
            str(k): round(float(v), 4) for k, v in per_size.items()
        }
        return out


def _percentile_ms(latencies_s: List[float], q: float) -> float:
    if not latencies_s:
        return 0.0
    ordered = sorted(latencies_s)
    idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
    return 1e3 * ordered[int(idx)]


def run_load(
    target: Any,
    trace: RequestTrace,
    row_shape: Tuple[int, ...],
    deterministic: bool = True,
    timeout_s: float = 5.0,
    seed: int = 0,
    settle_timeout_s: float = 30.0,
) -> LoadReport:
    """Open-loop replay of ``trace`` against ``target.submit``.

    The driver walks the trace clock: each request is submitted at its
    scheduled arrival (sleeping ahead, submitting immediately when
    behind — lag never thins the offered load). Completion latencies
    are recorded by future callbacks; after the last submit the driver
    waits up to ``settle_timeout_s`` for stragglers. No retries: a
    reject is DATA here (the server saying "over capacity"), and
    retrying would re-offer the load the measurement is trying to
    price.
    """
    rng = np.random.default_rng(seed)
    # Pre-build one obs buffer per distinct size (outside the timed
    # replay: the generator must not rate-limit itself on allocation).
    obs_by_size = {
        int(n): rng.standard_normal(
            (int(n), *row_shape), dtype=np.float32
        )
        for n in np.unique(trace.sizes)
    }
    lock = threading.Lock()
    latencies: List[float] = []
    by_size: Dict[int, List[float]] = {}
    counts = {"ok": 0, "rejected": 0, "timed_out": 0, "failed": 0}
    pending = threading.Semaphore(0)
    submitted = 0

    def _on_done(t_submit: float, rows: int, fut: Any) -> None:
        exc = fut.exception()
        now = time.perf_counter()
        with lock:
            if exc is None:
                counts["ok"] += 1
                latencies.append(now - t_submit)
                by_size.setdefault(rows, []).append(now - t_submit)
            elif isinstance(exc, BackpressureError):
                counts["rejected"] += 1
            elif isinstance(exc, (RequestTimeout, TimeoutError)):
                counts["timed_out"] += 1
            else:
                counts["failed"] += 1
        pending.release()

    t0 = time.perf_counter()
    next_at = t0
    for gap, n, slo in zip(
        trace.inter_arrival_s, trace.sizes, trace.slo_classes
    ):
        next_at += float(gap)
        lag = next_at - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        t_submit = time.perf_counter()
        try:
            fut = target.submit(
                obs_by_size[int(n)],
                deterministic=deterministic,
                timeout_s=timeout_s,
                slo_class=slo,
            )
        except BackpressureError:
            with lock:
                counts["rejected"] += 1
            submitted += 1
            pending.release()
            continue
        except Exception:  # noqa: BLE001 — overload data, not a crash
            with lock:
                counts["failed"] += 1
            submitted += 1
            pending.release()
            continue
        submitted += 1
        fut.add_done_callback(
            lambda f, t=t_submit, rows=int(n): _on_done(t, rows, f)
        )
    # The offered window closes at the LAST SUBMIT: the settle wait
    # below is measurement bookkeeping, not offered load — folding it
    # into the denominator would understate offered_rps exactly on the
    # overloaded probes (slow completions, long settles) where the
    # rate matters most.
    elapsed = time.perf_counter() - t0
    # Wait for in-flight stragglers (bounded — a wedged server must not
    # wedge the measurement).
    settle_deadline = time.perf_counter() + settle_timeout_s
    for _ in range(submitted):
        remaining = settle_deadline - time.perf_counter()
        if remaining <= 0 or not pending.acquire(timeout=remaining):
            break
    with lock:
        lat = list(latencies)
        done = dict(counts)
        sized = {
            n: _percentile_ms(v, 0.95) for n, v in by_size.items()
        }
    unresolved = submitted - sum(done.values())
    done["failed"] += max(0, unresolved)
    return LoadReport(
        per_size_p95_ms=sized,
        offered_rps=submitted / elapsed if elapsed > 0 else 0.0,
        duration_s=elapsed,
        submitted=submitted,
        ok=done["ok"],
        rejected=done["rejected"],
        timed_out=done["timed_out"],
        failed=done["failed"],
        p50_ms=_percentile_ms(lat, 0.50),
        p95_ms=_percentile_ms(lat, 0.95),
        p99_ms=_percentile_ms(lat, 0.99),
    )


def max_rate_at_slo(
    target: Any,
    row_shape: Tuple[int, ...],
    p95_target_ms: float,
    lo_rps: float = 50.0,
    hi_rps: float = 3200.0,
    probe_duration_s: float = 1.0,
    iterations: int = 6,
    max_loss: float = 0.01,
    seed: int = 0,
    size_mix: Sequence[Tuple[int, float]] = DEFAULT_SIZE_MIX,
    batch_fraction: float = 0.0,
    probe_retries: int = 0,
) -> Tuple[float, List[LoadReport]]:
    """Bisect offered rate for the highest replay holding the p95 SLO.

    Doubles ``hi_rps`` upward first while the SLO still holds there (so
    a too-low initial bracket cannot understate capacity), then bisects
    ``iterations`` times. Returns ``(best_passing_rate, reports)``;
    best rate 0.0 means even ``lo_rps`` violated the target. The same
    ``seed`` derives every probe's trace, so the search is
    deterministic given the server's behavior.

    ``probe_retries`` re-runs a FAILING probe up to that many times and
    accepts any passing attempt. On a shared box the noise is one-sided
    — contention only ever makes latency worse — so a rate the server
    holds in any window is genuinely within capacity, while a quiet-
    window pass can never overstate it. Retries keep one CPU hiccup
    from collapsing the whole search to 0.0 at the first probe."""
    reports: List[LoadReport] = []

    def probe(rate: float) -> LoadReport:
        trace = synthetic_trace(
            probe_duration_s,
            rate,
            seed=seed,
            size_mix=size_mix,
            batch_fraction=batch_fraction,
        )
        rep = run_load(target, trace, row_shape, seed=seed)
        reports.append(rep)
        for _ in range(probe_retries):
            if rep.meets(p95_target_ms, max_loss):
                break
            retry = run_load(target, trace, row_shape, seed=seed)
            reports.append(retry)
            if retry.meets(p95_target_ms, max_loss) or (
                retry.p95_ms < rep.p95_ms and retry.ok
            ):
                rep = retry
        return rep

    if not probe(lo_rps).meets(p95_target_ms, max_loss):
        return 0.0, reports
    best = lo_rps
    # Grow the bracket: if the ceiling still passes, capacity is higher
    # than the caller guessed. Cap check FIRST — at the cap the loop
    # must not burn (and then discard) one more full replay.
    grows = 0
    while grows < 4 and probe(hi_rps).meets(p95_target_ms, max_loss):
        best = hi_rps
        lo_rps, hi_rps = hi_rps, hi_rps * 2.0
        grows += 1
    for _ in range(iterations):
        mid = 0.5 * (lo_rps + hi_rps)
        if probe(mid).meets(p95_target_ms, max_loss):
            best, lo_rps = mid, mid
        else:
            hi_rps = mid
    return best, reports
