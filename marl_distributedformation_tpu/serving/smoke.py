"""Smoke benchmark: drive a live scheduler with a mixed-size request
stream and report the serving numbers that matter (bench.py's
one-JSON-line contract, applied to inference).

Used by ``scripts/serve_policy.py --smoke`` and the tier-1 serving test:
a handful of client threads submit observation batches whose sizes span
several rungs of the bucket ladder, so one run exercises coalescing,
padding, splitting, and the compile-once pin together. The report is a
flat dict — ``batch_occupancy_pct``, ``latency_p50_ms`` /
``latency_p95_ms`` / ``latency_p99_ms``, throughput, per-bucket compile
counts — ready to print as a single JSON line.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from marl_distributedformation_tpu.serving.client import ServingClient
from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    MicroBatchScheduler,
    RequestTimeout,
)

# Sizes straddling the default 1/8/64/512 ladder: singles, a mid rung,
# one just past a rung boundary (worst-case padding), one large.
DEFAULT_SIZES = (1, 3, 8, 9, 40, 100)


def run_smoke_benchmark(
    scheduler: MicroBatchScheduler,
    row_shape: Tuple[int, ...],
    sizes: Sequence[int] = DEFAULT_SIZES,
    duration_s: float = 2.0,
    num_clients: int = 4,
    deterministic: bool = True,
    seed: int = 0,
    registry: Optional[object] = None,
    scenario: Optional[str] = None,
    scenario_severity: float = 1.0,
) -> Dict[str, float]:
    """Run ``num_clients`` request loops for ``duration_s`` seconds.

    Each client cycles through ``sizes`` (offset by its index so the
    in-flight mix stays heterogeneous) with observations drawn from a
    seeded RNG. Returns the merged report; raises nothing on
    backpressure/timeouts — they are part of what is being measured.

    ``scenario`` perturbs the request observations with the named
    scenario's *sensor-noise* magnitudes from the registry
    (``scenarios/registry.py``, scaled by ``scenario_severity``) — smoke
    the serving path on the same disturbed inputs a robustness eval
    feeds the policy (unknown names fail fast with the registry listing).
    """
    obs_sigma = obs_bias_scale = 0.0
    if scenario is not None:
        from marl_distributedformation_tpu.scenarios import get_scenario

        spec = get_scenario(scenario)
        obs_sigma = float(spec.obs_noise_sigma) * float(scenario_severity)
        obs_bias_scale = float(spec.obs_bias) * float(scenario_severity)

    client = ServingClient(scheduler, max_retries=2)
    counts = {"ok": 0, "rejected": 0, "timed_out": 0}
    lock = threading.Lock()
    stop_at = time.perf_counter() + duration_s

    def loop(idx: int) -> None:
        rng = np.random.default_rng(seed + idx)
        if scenario is not None:
            # Constant per-client sensor bias (the layer's per-episode
            # bias). Drawn only under a scenario so scenario-free smokes
            # keep their seeded obs streams unchanged.
            bias = obs_bias_scale * rng.standard_normal(
                row_shape, dtype=np.float32
            )
        i = idx  # offset the size cycle per client
        while time.perf_counter() < stop_at:
            n = int(sizes[i % len(sizes)])
            i += 1
            obs = rng.standard_normal((n, *row_shape), dtype=np.float32)
            if scenario is not None:
                obs = obs + obs_sigma * rng.standard_normal(
                    obs.shape, dtype=np.float32
                ) + bias
            try:
                actions, _ = client.predict(
                    obs, deterministic=deterministic
                )
                assert actions.shape[0] == n
                with lock:
                    counts["ok"] += 1
            except BackpressureError:
                with lock:
                    counts["rejected"] += 1
            except RequestTimeout:
                with lock:
                    counts["timed_out"] += 1

    threads = [
        threading.Thread(target=loop, args=(i,), daemon=True)
        for i in range(num_clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 30.0)
    elapsed = time.perf_counter() - t0

    report = dict(scheduler.metrics.snapshot())
    report["duration_s"] = round(elapsed, 3)
    report["client_requests_ok"] = float(counts["ok"])
    report["client_rejected"] = float(counts["rejected"])
    report["client_timed_out"] = float(counts["timed_out"])
    report["requests_per_sec"] = (
        counts["ok"] / elapsed if elapsed > 0 else 0.0
    )
    report["rows_per_sec"] = (
        report["rows"] / elapsed if elapsed > 0 else 0.0
    )
    if scenario is not None:
        report["scenario"] = scenario
        report["scenario_severity"] = float(scenario_severity)
    for bucket, n in scheduler.engine.compile_counts().items():
        report[f"compiles_bucket_{bucket}"] = float(n)
    if registry is not None:
        report["model_swap_count"] = float(registry.swap_count)
        report["model_step"] = float(registry.active_step)
    return report
