"""Checkpoint watcher + atomic hot swap: the model side of serving.

The trainer drops ``rl_model_{steps}_steps.msgpack`` files into
``logs/{name}/`` (atomically — ``utils.checkpoint._write_atomic`` writes
a dot-prefixed temp file and renames, so discovery can never observe a
torn checkpoint). The registry polls that directory with
``latest_checkpoint`` and, when a newer step appears, restores it
against the serving template and swaps the active params under a lock.

Swap semantics (the hot-reload contract, docs/serving.md):

- **Atomic between batches** — the scheduler snapshots
  ``(params, step)`` once per micro-batch via :meth:`active`; a swap
  lands between snapshots, so every request in a batch is answered by
  exactly one model version and in-flight batches finish on the params
  they were dispatched with.
- **Same architecture only** — the restore is validated leaf-by-leaf
  against the live params (``restore_checkpoint_partial``), so a
  mismatched-architecture checkpoint is a clean recorded error, not a
  shape crash inside a compiled act function. The engine's jit cache is
  keyed on param shapes, which the validation holds fixed — a swap
  therefore never recompiles.
- **Never go backward, never go down** — older/equal steps are ignored,
  and any load failure keeps the previous params serving (the error is
  appended to :attr:`load_errors` and counted).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Deque, Optional, Tuple

from marl_distributedformation_tpu.compat.policy import (
    LoadedPolicy,
    load_checkpoint_raw,
)
from marl_distributedformation_tpu.utils.checkpoint import (
    checkpoint_step,
    latest_checkpoint,
    restore_state_dict_partial,
)


class ModelRegistry:
    """Serve-side view of one checkpoint directory.

    Args:
      log_dir: the ``logs/{name}/`` directory the trainer checkpoints to.
      policy: optionally a pre-built ``LoadedPolicy``; by default the
        newest checkpoint in ``log_dir`` is loaded (``env_params`` /
        ``act_dim`` forwarded to ``LoadedPolicy.from_checkpoint``).
      poll_interval_s: cadence of the background watcher thread
        (``start()``); ``refresh()`` may also be called directly.
      model_id: optional tenant-lane name (serving/tenancy): purely an
        identity stamp here — the single-engine registry still serves
        one model; the fleet's lane-keyed ``ReplicaRegistry`` cells are
        where multi-model state lives.
    """

    def __init__(
        self,
        log_dir: str | Path,
        policy: Optional[LoadedPolicy] = None,
        env_params: Any = None,
        act_dim: int = 2,
        poll_interval_s: float = 2.0,
        max_recorded_errors: int = 32,
        model_id: Optional[str] = None,
    ) -> None:
        import jax

        self.log_dir = Path(log_dir)
        self.model_id = model_id
        if policy is None:
            path = latest_checkpoint(self.log_dir)
            if path is None:
                raise FileNotFoundError(
                    f"no rl_model_*_steps.msgpack checkpoint under "
                    f"{self.log_dir} to serve"
                )
            policy = LoadedPolicy.from_checkpoint(
                path, act_dim=act_dim, env_params=env_params
            )
            step = checkpoint_step(path)
        else:
            # A pre-built policy's provenance is unknown — report step 0
            # so the first refresh() upgrades to whatever newest
            # checkpoint the directory holds (claiming the newest
            # on-disk step here would both mislabel results and block
            # that upgrade forever).
            step = 0
        self.policy = policy
        # Params live on device from the start: msgpack restores host
        # numpy trees, and handing those to the jitted act function
        # would re-upload the full weight tree every micro-batch.
        policy.params = jax.device_put(policy.params)
        self.poll_interval_s = poll_interval_s
        self.swap_count = 0  # graftlock: guarded-by=_lock
        self.load_errors: Deque[Tuple[str, str]] = deque(
            maxlen=max_recorded_errors
        )
        self._lock = threading.Lock()
        self._params = policy.params  # graftlock: guarded-by=_lock
        self._step = step  # graftlock: guarded-by=_lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- serving snapshot -----------------------------------------------

    def active(self) -> Tuple[Any, int]:
        """The ``(params, step)`` snapshot a micro-batch dispatches with."""
        with self._lock:
            return self._params, self._step

    @property
    def active_step(self) -> int:
        """Checkpoint step of the params currently serving (version
        pinning: every ``ServedResult`` carries the step it was computed
        with)."""
        with self._lock:
            return self._step

    # -- reload ---------------------------------------------------------

    def refresh(self) -> bool:
        """Check the directory once; swap if a newer checkpoint landed.
        Returns True on swap. Load failures (torn files are impossible by
        the atomic-write contract, but architecture mismatches and
        foreign files are not) keep the old params serving and are
        recorded in ``load_errors``."""
        path = latest_checkpoint(self.log_dir)
        if path is None:
            return False
        step = checkpoint_step(path)
        if step <= self.active_step:
            return False
        try:
            raw = load_checkpoint_raw(path)
            want = type(self.policy.model).__name__
            got = raw.get("policy", want)
            if got != want:
                raise ValueError(
                    f"checkpoint {path} was trained with policy {got!r}; "
                    f"this registry serves {want!r}"
                )
            restored = restore_state_dict_partial(
                raw, {"params": self._params}, origin=str(path)
            )
        except Exception as e:  # noqa: BLE001 — serving must not die
            self.load_errors.append((str(path), repr(e)))
            return False
        import jax

        # One host->device transfer at swap time; dispatches then reuse
        # device-resident buffers instead of re-uploading per batch.
        params = jax.device_put(restored["params"])
        with self._lock:
            if step <= self._step:
                # A concurrent refresh (watcher thread vs. a manual
                # call) finished a newer load while this one was
                # reading/validating — never swap backward.
                return False
            self._params = params
            self._step = step
            self.swap_count += 1
        return True

    # -- background watcher ---------------------------------------------

    def start(self) -> "ModelRegistry":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, name="model-registry-watch", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10.0)
        self._thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self.refresh()

    def __enter__(self) -> "ModelRegistry":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
