"""In-process serving client: the caller-side contract in one place.

``predict`` is deliberately SB3-shaped (obs in, actions out) so code
written against ``compat.policy.LoadedPolicy.predict`` ports by changing
one constructor. On top of the raw future API it adds the two behaviors
every well-behaved caller needs:

- **honor backpressure** — on :class:`BackpressureError` it sleeps the
  server-priced ``retry_after_s`` and retries, up to ``max_retries``
  times, instead of hammering a full queue;
- **bounded waiting** — the future wait is capped by the request's own
  timeout plus the retry budget, so a caller can never hang on a dead
  server.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import numpy as np

from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    MicroBatchScheduler,
    ServedResult,
)


class ServingClient:
    def __init__(
        self, scheduler: MicroBatchScheduler, max_retries: int = 3
    ) -> None:
        self.scheduler = scheduler
        self.max_retries = max_retries

    def predict(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
    ) -> Tuple[np.ndarray, int]:
        """Blocking predict; returns ``(actions, model_step)``.

        Raises ``RequestTimeout`` when the request's deadline passes,
        ``BackpressureError`` when the queue stayed full through every
        retry."""
        result = self.predict_full(obs, deterministic, timeout_s)
        return result.actions, result.model_step

    def predict_full(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
    ) -> ServedResult:
        wait_s = (
            timeout_s
            if timeout_s is not None
            else self.scheduler.default_timeout_s
        )
        for attempt in range(self.max_retries + 1):
            try:
                future = self.scheduler.submit(
                    obs, deterministic=deterministic, timeout_s=timeout_s
                )
            except BackpressureError as e:
                if attempt == self.max_retries:
                    raise
                time.sleep(e.retry_after_s)
                continue
            # Slack over the request's own deadline: the scheduler fails
            # expired requests itself; this outer bound only covers a
            # wedged worker.
            return future.result(timeout=wait_s + 5.0)
        raise AssertionError("unreachable")  # pragma: no cover
