"""In-process serving client: the caller-side contract in one place.

``predict`` is deliberately SB3-shaped (obs in, actions out) so code
written against ``compat.policy.LoadedPolicy.predict`` ports by changing
one constructor. On top of the raw future API it adds the two behaviors
every well-behaved caller needs:

- **honor backpressure** — on :class:`BackpressureError` it sleeps a
  capped-exponential backoff floored at the server-priced
  ``retry_after_s`` and retries, up to ``max_retries`` times (opt-in —
  ``max_retries=0`` surfaces every reject), instead of hammering a full
  queue;
- **bounded waiting** — the future wait is capped by the request's own
  timeout plus the retry budget, so a caller can never hang on a dead
  server.

The client is duck-typed over its target: anything with ``submit`` /
``default_timeout_s`` works, which is exactly the surface
``MicroBatchScheduler`` and ``fleet.FleetRouter`` share — the same
client code talks to one engine or a whole fleet.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple

import numpy as np

from marl_distributedformation_tpu.obs import new_trace_id
from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    ServedResult,
)


def backoff_s(
    attempt: int,
    retry_after_s: float,
    base_s: float = 0.05,
    cap_s: float = 2.0,
    jitter: Optional[Callable[[], float]] = None,
) -> float:
    """Capped-exponential backoff that honors the server's hint.

    The exponential leg ``base_s * 2**attempt`` is capped at ``cap_s``
    (a client must not end up sleeping minutes because it retried six
    times); the server-priced ``retry_after_s`` is a FLOOR, never capped
    — sleeping less than the server's own drain estimate guarantees
    another reject, which helps nobody. The exponential leg is what
    saves the server when its estimate is too optimistic: a queue that
    keeps rejecting at a tiny ``retry_after_s`` still sees this client
    back off harder every attempt.

    ``jitter`` (a zero-arg callable returning uniform [0, 1)) turns the
    exponential leg into FULL JITTER: the sleep becomes a random
    fraction of the capped-exponential delay, still floored at the
    server's ``retry_after_s``. Without it, a fleet-wide 429 or a
    failover storm synchronizes every client's clock — they all sleep
    the SAME deterministic delay and stampede back in lockstep, re-
    rejecting each other forever; spreading retries uniformly over the
    window drains the herd in one pass. ``None`` keeps the
    deterministic delay (single-caller tools, tests).
    """
    exp = min(cap_s, base_s * (2.0 ** attempt))
    if jitter is not None:
        exp *= jitter()
    return max(float(retry_after_s), exp)


class ServingClient:
    def __init__(
        self,
        scheduler,
        max_retries: int = 3,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        jitter: bool = True,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.scheduler = scheduler
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        # Full-jitter retries ship ON: a fleet of clients hitting the
        # same 429 must spread over the backoff window, not stampede
        # back in sync (backoff_s docstring). ``rng`` is injectable so
        # the distribution is pinnable in tests.
        self.jitter = bool(jitter)
        self._rng = rng if rng is not None else random.Random()

    def predict(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
        trace_id: Optional[str] = None,
        slo_class: str = "interactive",
    ) -> Tuple[np.ndarray, int]:
        """Blocking predict; returns ``(actions, model_step)``.

        Raises ``RequestTimeout`` when the request's deadline passes,
        ``BackpressureError`` when the queue stayed full through every
        retry (a batch-class request preempted by interactive traffic
        surfaces the same way and is retried the same way)."""
        result = self.predict_full(
            obs, deterministic, timeout_s, trace_id, slo_class
        )
        return result.actions, result.model_step

    def predict_full(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
        trace_id: Optional[str] = None,
        slo_class: str = "interactive",
    ) -> ServedResult:
        wait_s = (
            timeout_s
            if timeout_s is not None
            else self.scheduler.default_timeout_s
        )
        # ONE trace ID for the whole logical request: minted here when
        # the caller has none, re-sent on every backpressure retry, so
        # the server-side batch spans of all attempts correlate to this
        # single predict call (the whole point of retry observability).
        trace_id = trace_id or new_trace_id()
        for attempt in range(self.max_retries + 1):
            try:
                future = self.scheduler.submit(
                    obs, deterministic=deterministic, timeout_s=timeout_s,
                    trace_id=trace_id, slo_class=slo_class,
                )
                # Slack over the request's own deadline: the scheduler
                # fails expired requests itself; this outer bound only
                # covers a wedged worker. BackpressureError can ALSO
                # arrive through the future (a fleet router failing a
                # request over onto replicas that are all full) — it
                # consumes retry budget exactly like a submit-time
                # reject.
                return future.result(timeout=wait_s + 5.0)
            except BackpressureError as e:
                if attempt == self.max_retries:
                    raise
                time.sleep(
                    backoff_s(
                        attempt,
                        e.retry_after_s,
                        self.backoff_base_s,
                        self.backoff_cap_s,
                        jitter=self._rng.random if self.jitter else None,
                    )
                )
        raise AssertionError("unreachable")  # pragma: no cover
