"""In-process serving client: the caller-side contract in one place.

``predict`` is deliberately SB3-shaped (obs in, actions out) so code
written against ``compat.policy.LoadedPolicy.predict`` ports by changing
one constructor. On top of the raw future API it adds the two behaviors
every well-behaved caller needs:

- **honor backpressure** — on :class:`BackpressureError` it sleeps a
  capped-exponential backoff floored at the server-priced
  ``retry_after_s`` and retries, up to ``max_retries`` times (opt-in —
  ``max_retries=0`` surfaces every reject), instead of hammering a full
  queue;
- **bounded waiting** — the future wait is capped by the request's own
  timeout plus the retry budget, so a caller can never hang on a dead
  server.

The client is duck-typed over its target: anything with ``submit`` /
``default_timeout_s`` works, which is exactly the surface
``MicroBatchScheduler``, ``fleet.FleetRouter``, and
``mesh.MetaRouter`` share — the same client code talks to one engine,
a whole fleet, or a whole mesh.

**HTTP endpoints**: the target may instead be a base-URL string (or a
LIST of them — a fleet of frontends / mesh hosts). The client then
speaks the frontends' ``POST /v1/act`` protocol with client-side
failover: connection-refused and 5xx answers rotate to the next
endpoint, drawing from the SAME capped full-jitter retry budget as
backpressure — a dead frontend costs one attempt, never the whole
budget burned against one address.
"""

from __future__ import annotations

import http.client
import json
import random
import time
from typing import Callable, List, Optional, Tuple, Union

import numpy as np

from marl_distributedformation_tpu.obs import TRACE_HEADER, new_trace_id
from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    RequestTimeout,
    ServedResult,
)


def backoff_s(
    attempt: int,
    retry_after_s: float,
    base_s: float = 0.05,
    cap_s: float = 2.0,
    jitter: Optional[Callable[[], float]] = None,
) -> float:
    """Capped-exponential backoff that honors the server's hint.

    The exponential leg ``base_s * 2**attempt`` is capped at ``cap_s``
    (a client must not end up sleeping minutes because it retried six
    times); the server-priced ``retry_after_s`` is a FLOOR, never capped
    — sleeping less than the server's own drain estimate guarantees
    another reject, which helps nobody. The exponential leg is what
    saves the server when its estimate is too optimistic: a queue that
    keeps rejecting at a tiny ``retry_after_s`` still sees this client
    back off harder every attempt.

    ``jitter`` (a zero-arg callable returning uniform [0, 1)) turns the
    exponential leg into FULL JITTER: the sleep becomes a random
    fraction of the capped-exponential delay, still floored at the
    server's ``retry_after_s``. Without it, a fleet-wide 429 or a
    failover storm synchronizes every client's clock — they all sleep
    the SAME deterministic delay and stampede back in lockstep, re-
    rejecting each other forever; spreading retries uniformly over the
    window drains the herd in one pass. ``None`` keeps the
    deterministic delay (single-caller tools, tests).
    """
    exp = min(cap_s, base_s * (2.0 ** attempt))
    if jitter is not None:
        exp *= jitter()
    return max(float(retry_after_s), exp)


class ServingClient:
    def __init__(
        self,
        scheduler: Union[object, str, List[str]],
        max_retries: int = 3,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        jitter: bool = True,
        rng: Optional[random.Random] = None,
        default_timeout_s: float = 10.0,
    ) -> None:
        # A base-URL string (or a list of them) selects HTTP mode:
        # failover rotates over the endpoints on connection errors and
        # 5xx answers, sharing the one retry budget below.
        self._endpoints: Optional[List[str]] = None
        if isinstance(scheduler, str):
            self._endpoints = [scheduler.rstrip("/")]
        elif isinstance(scheduler, (list, tuple)):
            # A list is ALWAYS the endpoint form — a stray None from
            # unresolved config must fail here, loudly, not as an
            # AttributeError on the first predict.
            if not scheduler:
                raise ValueError("need at least one endpoint URL")
            bad = [e for e in scheduler if not isinstance(e, str)]
            if bad:
                raise TypeError(
                    f"endpoint list must be base-URL strings; got "
                    f"{bad[0]!r}"
                )
            self._endpoints = [e.rstrip("/") for e in scheduler]
        self._endpoint_idx = 0
        self.default_timeout_s = float(default_timeout_s)
        self.scheduler = scheduler
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        # Full-jitter retries ship ON: a fleet of clients hitting the
        # same 429 must spread over the backoff window, not stampede
        # back in sync (backoff_s docstring). ``rng`` is injectable so
        # the distribution is pinnable in tests.
        self.jitter = bool(jitter)
        self._rng = rng if rng is not None else random.Random()

    def predict(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
        trace_id: Optional[str] = None,
        slo_class: str = "interactive",
    ) -> Tuple[np.ndarray, int]:
        """Blocking predict; returns ``(actions, model_step)``.

        Raises ``RequestTimeout`` when the request's deadline passes,
        ``BackpressureError`` when the queue stayed full through every
        retry (a batch-class request preempted by interactive traffic
        surfaces the same way and is retried the same way)."""
        result = self.predict_full(
            obs, deterministic, timeout_s, trace_id, slo_class
        )
        return result.actions, result.model_step

    def predict_full(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
        trace_id: Optional[str] = None,
        slo_class: str = "interactive",
    ) -> ServedResult:
        if self._endpoints is not None:
            return self._predict_http(
                obs, deterministic, timeout_s, trace_id, slo_class
            )
        wait_s = (
            timeout_s
            if timeout_s is not None
            else self.scheduler.default_timeout_s
        )
        # ONE trace ID for the whole logical request: minted here when
        # the caller has none, re-sent on every backpressure retry, so
        # the server-side batch spans of all attempts correlate to this
        # single predict call (the whole point of retry observability).
        trace_id = trace_id or new_trace_id()
        for attempt in range(self.max_retries + 1):
            try:
                future = self.scheduler.submit(
                    obs, deterministic=deterministic, timeout_s=timeout_s,
                    trace_id=trace_id, slo_class=slo_class,
                )
                # Slack over the request's own deadline: the scheduler
                # fails expired requests itself; this outer bound only
                # covers a wedged worker. BackpressureError can ALSO
                # arrive through the future (a fleet router failing a
                # request over onto replicas that are all full) — it
                # consumes retry budget exactly like a submit-time
                # reject.
                return future.result(timeout=wait_s + 5.0)
            except BackpressureError as e:
                if attempt == self.max_retries:
                    raise
                time.sleep(
                    backoff_s(
                        attempt,
                        e.retry_after_s,
                        self.backoff_base_s,
                        self.backoff_cap_s,
                        jitter=self._rng.random if self.jitter else None,
                    )
                )
        raise AssertionError("unreachable")  # pragma: no cover

    # -- HTTP endpoint mode ----------------------------------------------

    def _predict_http(
        self,
        obs: np.ndarray,
        deterministic: bool,
        timeout_s: Optional[float],
        trace_id: Optional[str],
        slo_class: str,
    ) -> ServedResult:
        """``POST /v1/act`` against the endpoint list with client-side
        failover. One retry budget covers everything: a 429 consumes an
        attempt and sleeps the jittered backoff floored at the server's
        hint; a connection-refused or 5xx consumes an attempt and
        ROTATES to the next endpoint (so a dead frontend costs exactly
        one attempt per pass, never the whole budget); a 400/504 is the
        caller's own outcome and surfaces immediately."""
        wait_s = (
            timeout_s if timeout_s is not None else self.default_timeout_s
        )
        trace_id = trace_id or new_trace_id()
        body = json.dumps(
            {
                "obs": np.asarray(obs, np.float32).tolist(),
                "deterministic": bool(deterministic),
                "timeout_s": wait_s,
                "slo_class": slo_class,
            }
        ).encode()
        last_error: Optional[Exception] = None
        for attempt in range(self.max_retries + 1):
            url = self._endpoints[
                self._endpoint_idx % len(self._endpoints)
            ]
            retry_after = 0.0
            try:
                status, payload = self._post_act(
                    url, body, trace_id, wait_s
                )
            except (OSError, http.client.HTTPException) as e:
                # Nobody answered: fail over to the next address. The
                # backoff (no server hint: pure jittered exponential)
                # still applies so a fully-dead list backs off instead
                # of spinning.
                self._endpoint_idx += 1
                last_error = ConnectionError(
                    f"{url} unreachable: {e!r}"
                )
            else:
                if status == 200:
                    return ServedResult(
                        actions=np.asarray(
                            payload["actions"], np.float32
                        ),
                        model_step=int(payload["model_step"]),
                        latency_s=float(payload.get("latency_s", 0.0)),
                        replica=int(payload.get("replica", -1)),
                    )
                if status == 429:
                    retry_after = float(
                        payload.get("retry_after_s", 0.1)
                    )
                    # Another frontend may have capacity RIGHT NOW —
                    # rotate, and only honor THIS endpoint's drain
                    # estimate as a sleep floor when there is nowhere
                    # else to go (sleeping a busy host's quote before
                    # trying an idle peer pays the wrong bill).
                    self._endpoint_idx += 1
                    last_error = BackpressureError(retry_after)
                    if len(self._endpoints) > 1:
                        retry_after = 0.0
                elif status == 400:
                    raise ValueError(
                        str(payload.get("error", "bad request"))
                    )
                elif status == 504:
                    raise RequestTimeout(
                        str(payload.get("error", "deadline passed"))
                    )
                else:  # 5xx: that frontend is sick — rotate
                    self._endpoint_idx += 1
                    last_error = ConnectionError(
                        f"{url} answered {status}: "
                        f"{payload.get('error', '')!r}"
                    )
            if attempt == self.max_retries:
                raise last_error
            time.sleep(
                backoff_s(
                    attempt,
                    retry_after,
                    self.backoff_base_s,
                    self.backoff_cap_s,
                    jitter=self._rng.random if self.jitter else None,
                )
            )
        raise AssertionError("unreachable")  # pragma: no cover

    def _post_act(
        self, base_url: str, body: bytes, trace_id: str, wait_s: float
    ) -> Tuple[int, dict]:
        # Shared transport core (serving/mesh/rpc.py): one place to fix
        # connection handling for this client, the MetaRouter forward,
        # and the mesh RPC alike. Wait slack mirrors the frontends'
        # own: the server fails expired requests itself.
        from marl_distributedformation_tpu.serving.mesh.rpc import (
            post_json,
        )

        status, payload, _ = post_json(
            base_url,
            "/v1/act",
            body,
            headers={TRACE_HEADER: trace_id},
            timeout_s=wait_s + 10.0,
        )
        return status, payload
