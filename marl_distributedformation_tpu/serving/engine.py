"""Bucketed, jit-compiled policy act functions — the compiled core of
the serving stack.

Why buckets: a jitted function compiles one XLA program per input
*shape*. Serving traffic arrives at arbitrary batch sizes, and compiling
a multi-hundred-millisecond program per distinct size is the classic
silent serving killer (the same failure mode graftlint's RetraceGuard
exists to catch in training). The engine therefore compiles a small
ladder of fixed batch shapes — 1/8/64/512 by default — and pads every
request batch up to the next rung, so the total number of compilations
is bounded by ``len(buckets)`` for the lifetime of the process, no
matter what sizes clients send. Each bucket's act function is wrapped in
a :class:`RetraceGuard` with a budget of one trace; a retrace (weak-type
drift, dtype drift, a params structure change) raises instead of
silently recompiling per call.

Params are an *argument* of the compiled function, not a closure
constant: a hot-swapped checkpoint with the same architecture reuses the
existing executable — swapping weights never recompiles. The padded
observation buffer and the per-dispatch PRNG key are donated (both are
freshly built per call, so the engine never aliases a live buffer).

``dtype="bfloat16"`` opts a rung ladder into bf16 inference: each rung's
compiled program casts the float params and the obs to bf16 ON DEVICE
(part of the fused program — params stay f32 at rest, so hot swaps and
template validation are untouched and the jit cache keys never change),
computes the forward pass in bf16, and casts the actions back to f32
before the clip. The action divergence vs the f32 ladder is bounded the
same way the sharding parity gates are — an explicit amplification
budget (``tests/bf16_budget.py``), not a flat tolerance.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Settle jax_compat's global PRNG normalization (jax_threefry_partitionable)
# BEFORE any engine compiles: jax config values key the jit cache, so a
# later lazy import (e.g. parallel.mesh, pulled in the first time a fleet
# builds a mesh-sharded replica) flipping the flag would invalidate every
# already-warmed engine's programs — each next dispatch then retraces
# against its budget-1 guard and the replica circuit-breaks. Importing it
# here means "an engine exists" implies "the config is final".
from marl_distributedformation_tpu import jax_compat as _jax_compat  # noqa: F401
from marl_distributedformation_tpu.analysis.guards import (
    RetraceGuard,
    ledgered_jit,
)
from marl_distributedformation_tpu.models import distributions

# Powers-of-8-ish ladder: adjacent rungs are 8x apart, so padding waste
# is bounded (worst-case occupancy 1/8 just above a rung) while the
# compile count stays at 4 programs. See docs/serving.md for sizing.
DEFAULT_BUCKETS = (1, 8, 64, 512)


class BucketedPolicyEngine:
    """jit-compiled ``act`` over a ladder of fixed batch shapes.

    Args:
      policy: a ``compat.policy.LoadedPolicy`` (or anything with
        ``.model`` / ``.params`` of the same contract: ``model.apply``
        returns ``(mean, log_std, value)`` and is shape-polymorphic over
        leading batch axes).
      buckets: ascending batch-size ladder. Requests larger than the top
        rung are split into top-rung chunks plus a bucketed remainder.
      max_traces_per_bucket: RetraceGuard budget per rung. The default of
        1 is the serving contract — one bucket, one compile, ever; a
        second trace raises ``RetraceError`` naming the drifting
        signature.
      seed: base PRNG key for stochastic (non-deterministic) actions; a
        per-dispatch key is derived via ``fold_in`` on a dispatch
        counter, so no key is ever consumed twice.
      dtype: inference compute dtype. ``None``/"float32" serves f32;
        "bfloat16" compiles each rung with an in-program cast of float
        params + obs to bf16 (actions come back f32). Opt-in: the
        divergence budget is tests/bf16_budget.py's, not zero.
    """

    def __init__(
        self,
        policy: Any,
        buckets: Tuple[int, ...] = DEFAULT_BUCKETS,
        max_traces_per_bucket: Optional[int] = 1,
        seed: int = 0,
        dtype: Optional[str] = None,
    ) -> None:
        self.policy = policy
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        self.dtype = None if dtype in (None, "float32", "f32") else jnp.dtype(
            dtype
        )
        if self.dtype is not None and self.dtype != jnp.bfloat16:
            raise ValueError(
                f"inference dtype must be float32 or bfloat16, got {dtype!r}"
            )
        self.guards: Dict[int, RetraceGuard] = {
            b: RetraceGuard(
                f"serving-act-bucket{b}", max_traces=max_traces_per_bucket
            )
            for b in self.buckets
        }
        self._acts = {b: self._build_act(b) for b in self.buckets}
        self._base_key = jax.random.PRNGKey(seed)
        self._dispatches = 0  # graftlock: guarded-by=_lock
        self._lock = threading.Lock()
        # Trailing row shape, recorded on the first successful dispatch:
        # later mismatches fail fast as a ValueError instead of burning
        # a trace attempt inside jit.
        self._row_shape: Optional[Tuple[int, ...]] = None

    # -- compiled path --------------------------------------------------

    def _act_core(self, nn_params, obs, key, deterministic):
        """The traced act body, shared by every rung builder (the mesh
        subclass wraps it with an in-program key fold)."""
        model = self.policy.model
        cast = self.dtype
        if cast is not None:
            # In-program bf16 cast: params stay f32 at rest (swap /
            # validation contract untouched), the forward pass runs
            # in bf16, actions return f32. Float leaves only — step
            # counters and integer tables keep their dtypes.
            nn_params = jax.tree_util.tree_map(
                lambda x: (
                    x.astype(cast)
                    if jnp.issubdtype(x.dtype, jnp.floating)
                    else x
                ),
                nn_params,
            )
            obs = obs.astype(cast)
        mean, log_std, _ = model.apply(nn_params, obs)
        sampled = distributions.sample(key, mean, log_std)
        actions = jnp.where(
            deterministic, distributions.mode(mean), sampled
        )
        actions = actions.astype(jnp.float32)
        # Action-space clip, same contract as LoadedPolicy.predict.
        return jnp.clip(actions, -1.0, 1.0)

    def _build_act(self, bucket: int):
        def _act(nn_params, obs, key, deterministic):
            return self._act_core(nn_params, obs, key, deterministic)

        # obs + key are freshly materialized per dispatch — donate both.
        # ``deterministic`` rides as a traced bool scalar so ONE program
        # per bucket covers both modes (a static arg would double the
        # compile count for no win: the sampled branch is a cheap fused
        # normal draw). The CPU backend cannot alias input buffers
        # (donation there only emits a warning per compile), so donation
        # engages on accelerators only.
        donate = () if jax.default_backend() == "cpu" else (1, 2)
        dtype_tag = "bf16" if self.dtype is not None else "f32"
        return ledgered_jit(
            _act,
            self.guards[bucket],
            subsystem="serving",
            program=f"act_rung{bucket}_{dtype_tag}",
            donate_argnums=donate,
        )

    # -- bucketing ------------------------------------------------------

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest rung holding ``n`` rows (``n`` <= max_bucket)."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"{n} rows exceed the top bucket {self.max_bucket}")

    def plan(self, n: int) -> List[int]:
        """Rung sizes a dispatch of ``n`` rows pads into (top-rung chunks
        plus one bucketed remainder). ``sum(plan)`` is the padded
        capacity the batch occupies — the occupancy denominator."""
        if n <= 0:
            raise ValueError(f"need at least one row, got {n}")
        chunks = [self.max_bucket] * (n // self.max_bucket)
        rest = n % self.max_bucket
        if rest:
            chunks.append(self.bucket_for(rest))
        return chunks

    def compile_counts(self) -> Dict[int, int]:
        """Traces per rung so far (the serving contract: at most 1 each)."""
        return {b: g.count for b, g in self.guards.items()}

    @property
    def dtype_label(self) -> str:
        """Short dtype tag for metrics labels ("f32" / "bf16")."""
        return "bf16" if self.dtype == jnp.bfloat16 else "f32"

    # Dispatch hooks the mesh-sharded subclass overrides: the base
    # engine calls its jitted rung directly and lets jit place the
    # padded buffer on the params' device.
    is_sharded = False

    def _run(
        self,
        bucket: int,
        nn_params: Any,
        padded: np.ndarray,
        key: jax.Array,
        det: np.bool_,
    ):
        """One compiled-rung dispatch (the mesh subclass swaps in its
        AOT-executable path here)."""
        return self._acts[bucket](nn_params, padded, key, det)

    def _default_params(self) -> Any:
        return self.policy.params

    # -- host-side dispatch ---------------------------------------------

    def _next_key(self) -> jax.Array:
        with self._lock:
            count = self._dispatches
            self._dispatches += 1
        return jax.random.fold_in(self._base_key, count)

    def act(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        nn_params: Any = None,
    ) -> np.ndarray:
        """Actions for ``obs`` rows ``(n, *row_shape)``; pads to the next
        bucket, runs the compiled rung, slices the padding back off.
        ``nn_params=None`` uses the wrapped policy's own params (the
        registry passes its active snapshot instead)."""
        if nn_params is None:
            nn_params = self._default_params()
        obs = np.asarray(obs, np.float32)
        if obs.ndim < 2:
            raise ValueError(
                f"obs must be (n, *row_shape) with a leading batch axis, "
                f"got shape {obs.shape}"
            )
        n = obs.shape[0]
        if self._row_shape is not None and obs.shape[1:] != self._row_shape:
            raise ValueError(
                f"obs rows have shape {obs.shape[1:]}; this engine serves "
                f"{self._row_shape} rows (one compiled row shape per "
                "engine — the bucket ladder is the only shape axis)"
            )
        det = np.bool_(deterministic)  # strong dtype: no weak-type retrace
        outs: List[np.ndarray] = []
        start = 0
        for bucket in self.plan(n):
            k = min(bucket, n - start)
            padded = np.zeros((bucket,) + obs.shape[1:], np.float32)
            padded[:k] = obs[start : start + k]
            actions = self._run(
                bucket, nn_params, padded, self._next_key(), det
            )
            outs.append(np.asarray(actions)[:k])
            start += k
        self._row_shape = obs.shape[1:]
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
