"""Fleet smoke storm: mixed-size request traffic across every replica,
with the acceptance evidence in one flat report.

The single-engine smoke (serving/smoke.py) proves coalescing + padding +
compile-once on ONE engine; this storm drives the same mixed-size
request stream through the ROUTER so the fleet-only behaviors are what
gets exercised: routing across replicas, fleet backpressure, failover,
and — because every client records ``(completion order, model_step)``
into one shared log — the global step-monotonicity contract of the
coordinated hot swap.

The report is bench.py's one-JSON-line shape:

- ``requests_per_sec_fleet`` / merged latency percentiles — the fleet
  throughput headline.
- ``max_compiles_per_rung`` + per-replica ``replica{i}_compiles_bucket_{b}``
  — the RetraceGuard receipts: a storm of arbitrary sizes over N
  replicas must cost at most one compile per rung per replica, ever.
- ``step_monotonic_violations`` — count of responses whose
  ``model_step`` was lower than one already completed anywhere in the
  fleet. Zero is the coordinated-reload contract (reload.py).
- routed / rejected / failed-over / healthy-replica counters from
  ``FleetMetrics``.

``mid_storm`` is the chaos hook: a callable invoked once at
``mid_storm_at_s`` on its own thread — tests and the CLI use it to kill
a replica or land a coordinated swap while traffic flows.
"""

from __future__ import annotations

import threading
import time

# py3.10: concurrent.futures.TimeoutError is a distinct class from the
# builtin (merged in 3.11) — a wedged-worker wait must count as a
# timeout, not a failure.
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from marl_distributedformation_tpu.serving.fleet.router import FleetRouter
from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    RequestTimeout,
)
from marl_distributedformation_tpu.serving.smoke import DEFAULT_SIZES


def warmup_fleet(
    router: FleetRouter, row_shape: Tuple[int, ...]
) -> None:
    """Compile every rung on every replica once, before the clock runs.

    Uses each replica's REGISTRY params (device-committed), the same
    buffers the scheduler dispatches with — warming with the policy's
    host-resident params would compile against a different placement and
    the real dispatch would trip the budget-1 RetraceGuard."""
    for r in router.replicas:
        params, _ = r.registry.active()
        for bucket in r.engine.buckets:
            r.engine.act(
                np.zeros((bucket, *row_shape), np.float32),
                deterministic=True,
                nn_params=params,
            )


def run_fleet_smoke(
    router: FleetRouter,
    row_shape: Tuple[int, ...],
    sizes: Sequence[int] = DEFAULT_SIZES,
    duration_s: float = 2.0,
    num_clients: int = 4,
    deterministic: bool = True,
    seed: int = 0,
    coordinator: Optional[object] = None,
    mid_storm: Optional[Callable[[], None]] = None,
    mid_storm_at_s: float = 0.5,
    warmup: bool = True,
) -> Dict[str, float]:
    """Drive ``num_clients`` request loops through the router for
    ``duration_s`` seconds; returns the merged fleet report. Rejections
    and timeouts are measured, not raised. ``warmup`` pre-compiles every
    rung on every replica so the storm measures serving, not XLA."""
    if warmup:
        warmup_fleet(router, row_shape)
    counts = {"ok": 0, "rejected": 0, "timed_out": 0, "failed": 0}
    lock = threading.Lock()
    # One global completion log of model_steps in response completion
    # order — the monotonicity witness. Recorded via the router's
    # ``on_result`` hook, which runs INSIDE the serving replica's
    # batch-barrier region: the append provably precedes any later
    # coordinated swap, so the log cannot be reordered by a client
    # thread preempted between resolution and its own bookkeeping.
    completion_steps: list = []

    def record(result) -> None:
        with lock:
            completion_steps.append(int(result.model_step))

    stop_at = time.perf_counter() + duration_s

    def loop(idx: int) -> None:
        rng = np.random.default_rng(seed + idx)
        i = idx  # offset the size cycle per client
        while time.perf_counter() < stop_at:
            n = int(sizes[i % len(sizes)])
            i += 1
            obs = rng.standard_normal((n, *row_shape), dtype=np.float32)
            try:
                future = router.submit(
                    obs, deterministic=deterministic, on_result=record
                )
                result = future.result(
                    timeout=router.default_timeout_s + 5.0
                )
            except BackpressureError as e:
                with lock:
                    counts["rejected"] += 1
                time.sleep(min(0.05, e.retry_after_s))
                continue
            except (RequestTimeout, TimeoutError, FutureTimeoutError):
                with lock:
                    counts["timed_out"] += 1
                continue
            except Exception:  # noqa: BLE001 — incl. NoHealthyReplicas
                # Measured, not raised: a storm's job is to report what
                # the fleet did under fire, including the failures.
                with lock:
                    counts["failed"] += 1
                continue
            assert result.actions.shape[0] == n
            with lock:
                counts["ok"] += 1

    threads = [
        threading.Thread(target=loop, args=(i,), daemon=True)
        for i in range(num_clients)
    ]
    chaos = None
    if mid_storm is not None:

        def _chaos() -> None:
            time.sleep(mid_storm_at_s)
            mid_storm()

        chaos = threading.Thread(target=_chaos, daemon=True)
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    if chaos is not None:
        chaos.start()
    for t in threads:
        t.join(timeout=duration_s + 30.0)
    if chaos is not None:
        chaos.join(timeout=30.0)
    elapsed = time.perf_counter() - t0

    report = dict(router.snapshot())
    report["duration_s"] = round(elapsed, 3)
    report["client_requests_ok"] = float(counts["ok"])
    report["client_rejected"] = float(counts["rejected"])
    report["client_timed_out"] = float(counts["timed_out"])
    report["client_failed"] = float(counts["failed"])
    report["requests_per_sec_fleet"] = (
        counts["ok"] / elapsed if elapsed > 0 else 0.0
    )
    # Step monotonicity over the global completion order: a violation is
    # any response carrying a step older than one already returned.
    violations = 0
    high = None
    for step in completion_steps:
        if high is not None and step < high:
            violations += 1
        high = step if high is None else max(high, step)
    report["step_monotonic_violations"] = float(violations)
    if completion_steps:
        report["model_step_min"] = float(min(completion_steps))
        report["model_step_max"] = float(max(completion_steps))
    max_compiles = 0
    for r in router.replicas:
        for bucket, count in r.engine.compile_counts().items():
            report[f"replica{r.index}_compiles_bucket_{bucket}"] = float(
                count
            )
            max_compiles = max(max_compiles, count)
    report["max_compiles_per_rung"] = float(max_compiles)
    if coordinator is not None:
        report["fleet_swap_count"] = float(coordinator.swap_count)
        report["fleet_step"] = float(coordinator.fleet_step)
    return report
