"""FleetRouter: N compiled engines behind one submit surface.

Podracer (arXiv:2104.06272) scales TPU-native RL by replicating ONE
compiled program across devices behind a thin host-side dispatch layer;
this module is that layer for serving. Each replica is the whole proven
single-engine stack — ``BucketedPolicyEngine`` compiled against one
device plus its own ``MicroBatchScheduler`` worker thread — and the
router only does the three things a replica cannot do for itself:

- **Route.** Every request goes to the healthy replica with the lowest
  estimated drain time (queue depth x recent mean batch wall-clock —
  the quantity ``retry_after_s`` is already priced in). Joining the
  shortest *time* queue, not the shortest *length* queue, is what keeps
  a replica with a slow device from accumulating a latency tail.
- **Degrade.** A replica whose worker dies or whose budget-1
  RetraceGuard trips is circuit-broken: marked unhealthy, its queued
  requests transparently failed over to surviving replicas (bounded by
  ``max_failovers`` hops and the request's own deadline), and
  periodically re-probed (half-open: one routed request is the probe; a
  still-broken replica fails it over again and re-breaks). The fleet
  keeps serving at reduced width instead of dying.
- **Reject honestly.** Only when EVERY healthy replica rejects does the
  router raise fleet-level :class:`BackpressureError`, carrying the
  smallest ``retry_after_s`` any replica quoted — same contract as the
  single scheduler, so ``ServingClient`` works unchanged over a fleet.

Device placement is by params residency: each replica's weights are
``device_put`` onto its device and jit places each replica's compiled
programs there — no per-call device juggling, no sharding machinery in
the request path. The compiled path itself is untouched: the router is
strictly host-side, exactly the layer TF-Agents (arXiv:1709.02878)
identifies as where batched-inference throughput is won.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from marl_distributedformation_tpu.analysis.guards import RetraceError
from marl_distributedformation_tpu.obs import get_tracer
from marl_distributedformation_tpu.serving.engine import (
    DEFAULT_BUCKETS,
    BucketedPolicyEngine,
)
from marl_distributedformation_tpu.serving.fleet.metrics import FleetMetrics
from marl_distributedformation_tpu.serving.fleet.reload import ReplicaRegistry
from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    MicroBatchScheduler,
    SchedulerStopped,
)


class NoHealthyReplicas(RuntimeError):
    """Every replica is circuit-broken: the fleet is down, not busy."""


# Exceptions that indict the REPLICA, not the request: the router breaks
# the circuit and fails the request over. Everything else (RequestTimeout,
# a ValueError for malformed rows) is the caller's own outcome and
# propagates untouched — failing over a malformed request would just
# poison a second replica's dispatch.
_REPLICA_FAULTS = (SchedulerStopped, RetraceError)


@dataclasses.dataclass
class Replica:
    """One device's serving stack plus its circuit-breaker state.

    ``kind`` is "replicated" (one full-ladder engine on one device) or
    "sharded" (the mesh-backed big-rung engine, serving/sharded.py —
    ``device`` is then the engine's param-sharding tree, which is
    exactly what the reload coordinator ``device_put``s the restored
    tree against at commit, so a swap re-places the params under the
    partition rules once, fleet-wide, at the same barrier)."""

    index: int
    device: Any
    engine: BucketedPolicyEngine
    scheduler: MicroBatchScheduler
    registry: ReplicaRegistry
    # Circuit-breaker state is owned by the router's health lock: break,
    # readmit, and re-arm all mutate under ``FleetRouter._health_lock``.
    healthy: bool = True  # graftlock: guarded-by=_health_lock
    broken_at: float = 0.0  # graftlock: guarded-by=_health_lock
    break_reason: str = ""  # graftlock: guarded-by=_health_lock
    kind: str = "replicated"
    # Tenant lanes (serving/tenancy): one ``(params, step)`` cell PER
    # model lane, each with its own batch barrier. ``registry`` then
    # aliases the first lane's cell (legacy single-model readers); the
    # lane-keyed reload coordinator commits into these directly.
    registries: Optional[Dict[str, ReplicaRegistry]] = None


class FleetRouter:
    """Queue-depth routing + circuit breaking over per-device replicas.

    Args:
      policy: a ``compat.policy.LoadedPolicy`` (shared model definition;
        each replica gets its own device-resident copy of the params).
      devices: devices to replicate over; default ``jax.local_devices()``.
      num_replicas: replica count; default one per device. More replicas
        than devices cycle over them (useful for tests; on hardware one
        replica per device is the shape that makes sense).
      max_failovers: how many times one accepted request may be re-routed
        off a broken replica before its failure surfaces to the caller.
      probe_interval_s: how long a broken replica stays out of rotation
        before a half-open probe readmits it.
      initial_step: ``model_step`` the seeded params report (the fleet
        builder passes the checkpoint's step).
      logger: optional ``MetricsLogger``; the aggregated fleet snapshot
        is emitted every ``emit_every`` routed requests.
      sharded: optional ``serving.sharded.ShardedSpec`` — adds ONE
        mesh-backed big-rung replica (partition-rule params over a dp
        mesh slice, serving/sharded.py). Requests with at least
        ``sharded.route_min_rows`` rows route there first; small
        requests never do (the small rungs stay on the cheap
        single-device replicas). A broken sharded replica fails its
        big requests over to the replicated ladder like any other
        circuit break.
      trace_recorder: optional ``loadgen.TraceRecorder`` shared by every
        replica's scheduler — the interleaved record across schedulers
        IS the fleet-wide arrival process the elastic retuner replays
        (serving/elastic) and ``--record-trace`` dumps.
      lanes: optional ``model_id`` → ``(params, step)`` mapping — turns
        every replica multi-tenant (serving/tenancy): each lane gets
        its own device-resident ``ReplicaRegistry`` cell (own batch
        barrier, own monotonic step) per replica, the scheduler runs in
        tenant mode (per-lane admission queues + per-lane dispatch
        barriers), and ``submit`` requires a ``model_id``. All lanes
        share the ONE engine per replica — the params are traced
        inputs, so same-architecture lanes reuse the same compiled rung
        executables (``policy`` supplies the shared architecture; every
        lane's params must match its tree). Not combinable with
        ``sharded`` yet (docs/serving.md "Limits / next").
      tenant_max_queue: per-lane admission bound in lanes mode
        (default ``max_queue``, applied per lane).
    """

    def __init__(
        self,
        policy: Any,
        devices: Optional[Sequence[Any]] = None,
        num_replicas: Optional[int] = None,
        buckets: Tuple[int, ...] = DEFAULT_BUCKETS,
        window_ms: float = 2.0,
        max_queue: int = 256,
        default_timeout_s: float = 10.0,
        seed: int = 0,
        max_failovers: int = 1,
        probe_interval_s: float = 1.0,
        initial_step: int = 0,
        metrics: Optional[FleetMetrics] = None,
        logger: Any = None,
        emit_every: int = 200,
        sharded: Any = None,
        lanes: Any = None,
        tenant_max_queue: Optional[int] = None,
        trace_recorder: Any = None,
    ) -> None:
        import jax

        devs = list(devices) if devices is not None else jax.local_devices()
        if not devs:
            raise ValueError("need at least one device to build a fleet")
        n = len(devs) if num_replicas is None else int(num_replicas)
        if n < 1:
            raise ValueError(f"need at least one replica, got {n}")
        if lanes is not None and sharded is not None:
            raise ValueError(
                "tenant lanes over the sharded big-rung slice are not "
                "supported yet (docs/serving.md 'Limits / next')"
            )
        if lanes is not None and not lanes:
            raise ValueError("lanes must declare at least one model lane")
        self.policy = policy
        self.lane_ids: Tuple[str, ...] = (
            tuple(lanes) if lanes is not None else ()
        )
        self.default_timeout_s = default_timeout_s
        self.max_failovers = max_failovers
        self.probe_interval_s = probe_interval_s
        self.metrics = metrics or FleetMetrics()
        self.logger = logger
        self.emit_every = emit_every
        self.trace_recorder = trace_recorder
        # Construction knobs kept for the elastic rebuild path
        # (build_replica / build_sharded_replica): a re-split builds
        # replicas the same way the constructor did, just later.
        self._devices = devs
        self._buckets = tuple(buckets)
        self._window_ms = float(window_ms)
        self._max_queue = int(max_queue)
        self._seed = int(seed)
        self._health_lock = threading.Lock()
        self._stopping = False
        self.replicas: List[Replica] = []
        for i in range(n):
            dev = devs[i % len(devs)]
            engine = BucketedPolicyEngine(
                policy, buckets=buckets, seed=seed + i
            )
            if lanes is not None:
                # One (params, step) cell per lane, all device-resident
                # on THIS replica's device; the ONE engine serves every
                # lane (params are traced inputs — same-arch lanes share
                # its compiled rungs).
                registries = {
                    mid: ReplicaRegistry(
                        jax.device_put(lane_params, dev),
                        step=lane_step,
                        device=dev,
                    )
                    for mid, (lane_params, lane_step) in lanes.items()
                }
                registry = registries[next(iter(registries))]
                scheduler = MicroBatchScheduler(
                    engine,
                    registries=registries,
                    max_queue=max_queue,
                    tenant_max_queue=tenant_max_queue,
                    window_ms=window_ms,
                    default_timeout_s=default_timeout_s,
                    trace_recorder=trace_recorder,
                )
            else:
                registries = None
                registry = ReplicaRegistry(
                    jax.device_put(policy.params, dev),
                    step=initial_step,
                    device=dev,
                )
                scheduler = MicroBatchScheduler(
                    engine,
                    registry=registry,
                    max_queue=max_queue,
                    window_ms=window_ms,
                    default_timeout_s=default_timeout_s,
                    trace_recorder=trace_recorder,
                )
            self.replicas.append(
                Replica(
                    index=i,
                    device=dev,
                    engine=engine,
                    scheduler=scheduler,
                    registry=registry,
                    registries=registries,
                )
            )
        self.sharded_replica: Optional[Replica] = None
        self._sharded_min_rows = 0
        if sharded is not None:
            from marl_distributedformation_tpu.parallel.mesh import (
                make_mesh,
            )
            from marl_distributedformation_tpu.serving.sharded import (
                ShardedPolicyEngine,
            )

            mesh = make_mesh(
                dict(sharded.axis_sizes or {"dp": len(devs)})
            )
            sh_engine = ShardedPolicyEngine(
                policy,
                mesh,
                buckets=sharded.buckets,
                rules=sharded.rules,
                seed=seed + n,
                dtype=sharded.dtype,
            )
            # The registry cell holds a mesh-placed copy and — the key
            # move — records the param-sharding TREE as its "device":
            # the reload coordinator's per-replica
            # ``device_put(restored, registry.device)`` then re-places
            # every swap under the partition rules, once, at the same
            # fleet batch barrier as everyone else.
            # The engine already placed its own copy at construction —
            # seed the registry with THAT tree instead of sharding a
            # second mesh-resident copy (double param memory on the
            # slice is exactly what sharded serving exists to avoid;
            # both readers are read-only and a swap replaces only the
            # registry's pointer).
            sh_registry = ReplicaRegistry(
                sh_engine._params_on_mesh,
                step=initial_step,
                device=sh_engine.param_shardings,
            )
            sh_scheduler = MicroBatchScheduler(
                sh_engine,
                registry=sh_registry,
                max_queue=max_queue,
                window_ms=(
                    window_ms
                    if sharded.window_ms is None
                    else sharded.window_ms
                ),
                default_timeout_s=default_timeout_s,
                trace_recorder=trace_recorder,
            )
            self.sharded_replica = Replica(
                index=n,
                device=mesh,
                engine=sh_engine,
                scheduler=sh_scheduler,
                registry=sh_registry,
                kind="sharded",
            )
            self.replicas.append(self.sharded_replica)
            self._sharded_min_rows = sharded.route_min_rows
        # Replica indices are never reused across re-splits: metric and
        # report keys (``replica{i}_*``) stay unambiguous for the whole
        # process lifetime.
        self._next_index = len(self.replicas)  # graftlock: guarded-by=_health_lock

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "FleetRouter":
        self._stopping = False
        for r in self.replicas:
            r.scheduler.start()
        return self

    def stop(self) -> None:
        # Flag first: the drain of each scheduler fails its queued
        # futures with SchedulerStopped, and the failover callbacks must
        # not bounce those between replicas that are also shutting down.
        self._stopping = True
        for r in self.replicas:
            r.scheduler.stop()

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- client side -----------------------------------------------------

    def submit(
        self,
        obs: np.ndarray,
        deterministic: bool = True,
        timeout_s: Optional[float] = None,
        on_result: Optional[Any] = None,
        trace_id: Optional[str] = None,
        slo_class: str = "interactive",
        model_id: Optional[str] = None,
    ) -> Future:
        """Route one request; returns a future resolving to
        ``ServedResult`` (with ``.replica`` set). Raises
        :class:`BackpressureError` when every healthy replica is full,
        :class:`NoHealthyReplicas` when the whole fleet is broken.
        ``model_id`` names the tenant lane (required in lanes mode —
        the schedulers validate it against the declared lanes).

        ``on_result(result)``, if given, runs at resolution time INSIDE
        the serving replica's batch-barrier region — i.e. strictly
        before the reload coordinator can commit a swap. That makes it
        the race-free place to observe fleet-wide response completion
        order (the smoke storm's step-monotonicity witness); an
        observer that waits on the returned future instead can be
        preempted between resolution and its own bookkeeping. Keep it
        cheap: it runs on the dispatch path."""
        timeout = (
            self.default_timeout_s if timeout_s is None else timeout_s
        )
        deadline = time.perf_counter() + timeout
        outer: Future = Future()
        replica, inner = self._route(
            obs, deterministic, timeout_s, set(), trace_id, slo_class,
            model_id,
        )
        self._chain(
            replica, inner, outer, obs, deterministic, timeout_s,
            hops=0, tried={replica.index}, deadline=deadline,
            on_result=on_result, trace_id=trace_id, slo_class=slo_class,
            model_id=model_id,
        )
        return outer

    # -- routing ---------------------------------------------------------

    def _route(
        self,
        obs: np.ndarray,
        deterministic: bool,
        timeout_s: Optional[float],
        tried: Set[int],
        trace_id: Optional[str] = None,
        slo_class: str = "interactive",
        model_id: Optional[str] = None,
    ) -> Tuple[Replica, Future]:
        """Submit to the best healthy replica not in ``tried``; walk down
        the drain-time ordering past individually-full replicas.

        Big-rung preference: a request of at least ``sharded.min_rows``
        rows tries the mesh-backed sharded replica FIRST (that is what
        the slice exists for), then falls through to the replicated
        ladder on backpressure or a break. Small requests route to the
        sharded replica only as a LAST resort (its ladder starts at the
        big rungs, so a 1-row request there pads 64x — but serving it
        wastefully still beats a 503 when every replicated replica is
        broken or full)."""
        self._probe_broken()
        rows = int(obs.shape[0]) if hasattr(obs, "shape") else 0
        big = (
            self.sharded_replica is not None
            and rows >= self._sharded_min_rows
        )

        def _pref(r: Replica) -> int:
            if r.kind == "sharded":
                return 0 if big else 2
            return 1

        candidates = sorted(
            (
                r
                for r in self.replicas
                if r.healthy and r.index not in tried
            ),
            key=lambda r: (
                _pref(r),
                r.scheduler.estimated_drain_s(model_id),
            ),
        )
        rejections: List[BackpressureError] = []
        for r in candidates:
            if not r.scheduler.alive:
                self._break(r, "worker thread dead at routing time")
                continue
            try:
                inner = r.scheduler.submit(
                    obs, deterministic=deterministic, timeout_s=timeout_s,
                    trace_id=trace_id, slo_class=slo_class,
                    model_id=model_id,
                )
                return r, inner
            except BackpressureError as e:
                rejections.append(e)
            except ValueError:
                raise  # malformed request: the caller's problem, as-is
            except RuntimeError as e:
                # "scheduler not started" / racing a concurrent stop().
                self._break(r, f"submit failed: {e!r}")
        if rejections:
            self.metrics.record_rejected()
            raise BackpressureError(
                min(e.retry_after_s for e in rejections)
            )
        raise NoHealthyReplicas(
            f"all {len(self.replicas)} replicas are circuit-broken: "
            + "; ".join(
                f"replica{r.index}: {r.break_reason or 'unknown'}"
                for r in self.replicas
                if not r.healthy
            )
        )

    def _chain(
        self,
        replica: Replica,
        inner: Future,
        outer: Future,
        obs: np.ndarray,
        deterministic: bool,
        timeout_s: Optional[float],
        hops: int,
        tried: Set[int],
        deadline: float,
        on_result: Optional[Any] = None,
        trace_id: Optional[str] = None,
        slo_class: str = "interactive",
        model_id: Optional[str] = None,
    ) -> None:
        """Resolve ``outer`` from ``inner``, failing over replica faults
        onto a fresh replica while the hop budget and deadline allow."""

        def _done(fut: Future) -> None:
            exc = fut.exception()
            if exc is None:
                result = dataclasses.replace(
                    fut.result(), replica=replica.index
                )
                count = self.metrics.record_routed(replica.index)
                if on_result is not None:
                    on_result(result)
                outer.set_result(result)
                if (
                    self.logger is not None
                    and count % self.emit_every == 0
                ):
                    # Off the dispatch path: this callback runs inside
                    # the replica's batch-barrier region, and snapshot()
                    # walks every replica's latency window — doing that
                    # under the lock would stretch every batch AND the
                    # coordinator's commit wait.
                    threading.Thread(
                        target=self._emit_snapshot,
                        args=(count,),
                        name="fleet-metrics-emit",
                        daemon=True,
                    ).start()
                return
            if isinstance(exc, _REPLICA_FAULTS) and not self._stopping:
                self._break(replica, repr(exc))
                if (
                    hops < self.max_failovers
                    and time.perf_counter() < deadline
                ):
                    try:
                        nxt, nfut = self._route(
                            obs, deterministic, timeout_s, tried,
                            trace_id, slo_class, model_id,
                        )
                    except Exception as routing_exc:  # noqa: BLE001
                        outer.set_exception(routing_exc)
                        return
                    self.metrics.record_failover()
                    self._chain(
                        nxt, nfut, outer, obs, deterministic, timeout_s,
                        hops + 1, tried | {nxt.index}, deadline,
                        on_result=on_result, trace_id=trace_id,
                        slo_class=slo_class, model_id=model_id,
                    )
                    return
            outer.set_exception(exc)

        inner.add_done_callback(_done)

    def _emit_snapshot(self, count: int) -> None:
        try:
            self.logger.log(self.snapshot(), step=count)
        except Exception:  # noqa: BLE001 — observability never kills serving
            pass

    # -- health ----------------------------------------------------------

    def _break(self, replica: Replica, reason: str) -> None:
        with self._health_lock:
            if not replica.healthy:
                return
            replica.healthy = False
            replica.broken_at = time.monotonic()
            replica.break_reason = reason
        self.metrics.record_break()
        if not replica.scheduler.alive:
            # A DEAD worker's queued futures would wedge their callers
            # forever (nothing will ever dispatch them). Fail them with
            # SchedulerStopped now — the failover callbacks re-route
            # them to surviving replicas like any replica fault. Guarded
            # on liveness: a live worker (RetraceError break) still owns
            # and drains its own queue.
            replica.scheduler.fail_queued()
        # Circuit break = an incident: snapshot the trace ring while the
        # pre-break dispatch history is still in it (flight recorder,
        # when configured) — outside the health lock, it does file IO.
        get_tracer().incident(
            "circuit_break",
            replica=replica.index,
            reason=reason,
            healthy_replicas=self.healthy_replicas,
        )

    def _probe_broken(self) -> None:
        """Half-open probing on the routing path: a broken replica whose
        probe interval elapsed and whose worker is alive is readmitted;
        its next routed request is the real probe (failure re-breaks
        it). A dead worker can never be readmitted."""
        now = time.monotonic()
        for r in self.replicas:
            if r.healthy or now - r.broken_at < self.probe_interval_s:
                continue
            self.metrics.record_probe()
            if r.scheduler.alive:
                with self._health_lock:
                    if not r.healthy:
                        r.healthy = True
                        r.break_reason = ""
            else:
                # Re-arm under the same lock every other breaker-state
                # write holds — two routing threads probing the same
                # dead replica must not interleave with a concurrent
                # break/readmit.
                with self._health_lock:
                    r.broken_at = now  # still dead; re-check next interval

    def kill_replica(self, index: int, reason: str = "killed") -> None:
        """Stop one replica's worker (chaos hook, used by tests and the
        smoke storm). Its queued requests fail with ``SchedulerStopped``
        and the failover path re-routes them to surviving replicas."""
        # Lookup by Replica.index, not list position: after an elastic
        # re-split the two diverge (indices are never reused).
        replica = next(
            (r for r in self.replicas if r.index == index), None
        )
        if replica is None:
            raise KeyError(f"no replica with index {index}")
        self._break(replica, reason)
        replica.scheduler.stop()

    @property
    def healthy_replicas(self) -> int:
        return sum(1 for r in self.replicas if r.healthy)

    # -- elasticity (serving/elastic) ------------------------------------

    def fleet_params(self) -> Tuple[Any, int]:
        """The ``(params, step)`` the fleet currently serves — a
        replicated replica's cell when one exists (host-transferable
        single-device tree), else the sharded cell. The coordinator
        commits every cell identically, so any cell is authoritative."""
        for r in self.replicas:
            if r.kind == "replicated":
                return r.registry.active()
        return self.replicas[0].registry.active()

    def _alloc_index(self) -> int:
        with self._health_lock:
            index = self._next_index
            self._next_index += 1
            return index

    def build_replica(
        self,
        device: Any = None,
        buckets: Optional[Tuple[int, ...]] = None,
        window_ms: Optional[float] = None,
    ) -> Replica:
        """Build one UNROUTED replicated replica at the fleet's current
        ``(params, step)`` — the elastic prewarm path. The scheduler is
        constructed but NOT started and nothing routes here until the
        replica lands via ``FleetReloadCoordinator.commit_resplit``;
        the caller warms every rung (with the registry's params, the
        ``warmup_fleet`` contract) off the serving path first."""
        import jax

        if self.lane_ids:
            raise ValueError(
                "elastic re-split over tenant lanes is not supported "
                "yet (docs/serving.md 'Limits / next')"
            )
        index = self._alloc_index()
        dev = (
            device
            if device is not None
            else self._devices[index % len(self._devices)]
        )
        params, step = self.fleet_params()
        engine = BucketedPolicyEngine(
            self.policy,
            buckets=tuple(buckets) if buckets is not None else self._buckets,
            seed=self._seed + index,
        )
        registry = ReplicaRegistry(
            jax.device_put(params, dev), step=step, device=dev
        )
        scheduler = MicroBatchScheduler(
            engine,
            registry=registry,
            max_queue=self._max_queue,
            window_ms=(
                self._window_ms if window_ms is None else float(window_ms)
            ),
            default_timeout_s=self.default_timeout_s,
            trace_recorder=self.trace_recorder,
        )
        return Replica(
            index=index,
            device=dev,
            engine=engine,
            scheduler=scheduler,
            registry=registry,
        )

    def build_sharded_replica(self, spec: Any) -> Replica:
        """Build one UNROUTED mesh-backed big-rung replica from a
        ``serving.sharded.ShardedSpec`` at the fleet's current
        ``(params, step)`` — same construction as the boot path, but
        the slice adopts the params the fleet serves NOW (the boot copy
        from ``policy.params`` would resurrect a stale step after any
        reload). Routing of big requests flips to the new slice only
        when ``commit_resplit`` lands it."""
        from marl_distributedformation_tpu.parallel.mesh import make_mesh
        from marl_distributedformation_tpu.serving.sharded import (
            ShardedPolicyEngine,
        )

        if self.lane_ids:
            raise ValueError(
                "elastic re-split over tenant lanes is not supported "
                "yet (docs/serving.md 'Limits / next')"
            )
        index = self._alloc_index()
        mesh = make_mesh(
            dict(spec.axis_sizes or {"dp": len(self._devices)})
        )
        engine = ShardedPolicyEngine(
            self.policy,
            mesh,
            buckets=spec.buckets,
            rules=spec.rules,
            seed=self._seed + index,
            dtype=spec.dtype,
        )
        params, step = self.fleet_params()
        # Adopt the CURRENT fleet params onto the slice (replacing the
        # boot copy — no double residency) and seed the registry from
        # the same tree, exactly like the constructor's sharded path.
        engine.adopt_params(params)
        registry = ReplicaRegistry(
            engine._params_on_mesh,
            step=step,
            device=engine.param_shardings,
        )
        scheduler = MicroBatchScheduler(
            engine,
            registry=registry,
            max_queue=self._max_queue,
            window_ms=(
                self._window_ms
                if spec.window_ms is None
                else spec.window_ms
            ),
            default_timeout_s=self.default_timeout_s,
            trace_recorder=self.trace_recorder,
        )
        return Replica(
            index=index,
            device=mesh,
            engine=engine,
            scheduler=scheduler,
            registry=registry,
            kind="sharded",
        )

    # graftlock: holds=batch_lock
    def _commit_resplit(
        self,
        add: Sequence[Replica],
        retire: Set[int],
        sharded_min_rows: Optional[int] = None,
    ) -> None:
        """Swap routing membership — coordinator-only, called from
        ``FleetReloadCoordinator.commit_resplit`` at the fleet batch
        barrier with every CURRENT replica's lock held (zero batches in
        flight anywhere). One list assignment under the health lock:
        requests racing the commit see either the old set or the new
        set, never a torn one."""
        with self._health_lock:
            kept = [r for r in self.replicas if r.index not in retire]
            self.replicas = kept + list(add)
            shards = [r for r in self.replicas if r.kind == "sharded"]
            self.sharded_replica = shards[-1] if shards else None
            if self.sharded_replica is None:
                self._sharded_min_rows = 0
            elif sharded_min_rows is not None:
                self._sharded_min_rows = int(sharded_min_rows)

    def drain_replica(
        self, replica: Replica, timeout_s: float = 10.0
    ) -> bool:
        """Drain-before-retire: wait for a DE-ROUTED replica (already
        swapped out by ``commit_resplit`` — no new submits can reach
        it) to finish its queued work and go idle, then stop its
        worker. Returns True on a clean drain; on timeout the worker
        is stopped anyway and its still-queued requests fail with
        ``SchedulerStopped``, which the normal failover path re-routes
        onto the live replicas."""
        deadline = time.perf_counter() + timeout_s
        drained = False
        while time.perf_counter() < deadline:
            sched = replica.scheduler
            if sched.queue_depth == 0 and not sched._busy:
                drained = True
                break
            time.sleep(0.002)
        replica.scheduler.stop()
        return drained

    # -- observability ---------------------------------------------------

    def snapshot(self) -> Dict[str, float]:
        """Aggregated fleet metrics (fleet/metrics.py) plus the newest
        step any replica serves (in lanes mode: the newest step any
        LANE serves, with per-lane ``model_{id}__step`` keys riding
        along — obs/export.py folds them into one ``model``-labeled
        family)."""
        snap = self.metrics.snapshot(self.replicas)
        if self.lane_ids:
            steps = self.lane_steps()
            for mid, step in steps.items():
                snap[f"model_{mid}__step"] = float(step)
                snap[f"model_{mid}__queue_depth"] = float(
                    sum(
                        r.scheduler.lane_queue_depth(mid)
                        for r in self.replicas
                        if r.registries is not None
                    )
                )
            snap["model_step"] = float(max(steps.values()))
        else:
            snap["model_step"] = float(
                max(r.registry.active_step for r in self.replicas)
            )
        return snap

    def lane_steps(self) -> Dict[str, int]:
        """Per-lane served step (lanes mode): the newest step any
        replica's cell for that lane holds — each lane is monotonic
        independently (per-model step monotonicity)."""
        return {
            mid: max(
                r.registries[mid].active_step
                for r in self.replicas
                if r.registries is not None
            )
            for mid in self.lane_ids
        }

    def compile_counts(self) -> Dict[int, Dict[int, int]]:
        """Per-replica per-rung trace counts — the fleet-wide
        compile-once receipt (every value must be <= 1)."""
        return {
            r.index: r.engine.compile_counts() for r in self.replicas
        }
