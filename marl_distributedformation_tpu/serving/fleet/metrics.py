"""Fleet observability: the aggregate view a multi-replica server needs.

Per-replica ``ServingMetrics`` already exist (each scheduler owns one);
what the fleet layer adds is the numbers that only make sense ABOVE the
replicas:

- ``fleet_routed_total`` / per-replica routed counts — routing skew is
  the router's core behavior; a flat-lined replica under load means the
  drain estimator or the health state is wrong.
- ``fleet_failed_over_total`` — requests transparently re-routed off a
  dying replica. Nonzero during an incident is the system WORKING;
  nonzero in steady state means a replica is flapping.
- ``fleet_rejected_total`` — fleet-level backpressure: every healthy
  replica was full. This is the number capacity planning watches.
- ``fleet_breaks_total`` / ``fleet_healthy_replicas`` — circuit-breaker
  activity and the live serving width.
- merged ``latency_p50/p95/p99_ms`` — computed over the raw latency
  samples of every replica pooled together (averaging per-replica
  percentiles is statistically meaningless).

``snapshot(replicas)`` returns the flat ``{name: float}`` dict shape the
rest of the repo logs through ``utils.logging.MetricsLogger``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence

from marl_distributedformation_tpu.obs.metrics import get_registry
from marl_distributedformation_tpu.serving.metrics import ServingMetrics


class FleetMetrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.routed_total = 0  # graftlock: guarded-by=_lock
        self.rejected_total = 0  # graftlock: guarded-by=_lock
        self.failed_over_total = 0  # graftlock: guarded-by=_lock
        self.breaks_total = 0  # graftlock: guarded-by=_lock
        self.probes_total = 0  # graftlock: guarded-by=_lock
        self._routed_per_replica: Dict[int, int] = {}  # graftlock: guarded-by=_lock

    # -- recording (router side) ----------------------------------------

    def record_routed(self, replica: int) -> int:
        """Returns the new fleet-wide routed count (the router uses it
        to pace logger emission)."""
        with self._lock:
            self.routed_total += 1
            self._routed_per_replica[replica] = (
                self._routed_per_replica.get(replica, 0) + 1
            )
            return self.routed_total

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected_total += 1

    def record_failover(self) -> None:
        with self._lock:
            self.failed_over_total += 1

    def record_break(self) -> None:
        with self._lock:
            self.breaks_total += 1

    def record_probe(self) -> None:
        with self._lock:
            self.probes_total += 1

    # -- reading ---------------------------------------------------------

    def routed_per_replica(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._routed_per_replica)

    def snapshot(self, replicas: Sequence) -> Dict[str, float]:
        """Flat float dict over the fleet counters plus every replica's
        own metrics; ``replicas`` is the router's replica list (each
        exposes ``.index``, ``.healthy``, ``.scheduler.metrics``)."""
        with self._lock:
            out: Dict[str, float] = {
                "fleet_replicas": float(len(replicas)),
                "fleet_routed_total": float(self.routed_total),
                "fleet_rejected_total": float(self.rejected_total),
                "fleet_failed_over_total": float(self.failed_over_total),
                "fleet_breaks_total": float(self.breaks_total),
                "fleet_probes_total": float(self.probes_total),
            }
            routed = dict(self._routed_per_replica)
        merged: List[float] = []
        healthy = 0
        # Per-rung shard/dtype gauges: one entry per (rung, dtype) the
        # fleet's engines serve — "is this rung mesh-sharded", "what has
        # it compiled" — folded into labeled Prometheus families by
        # obs/export.py (``rung_sharded{rung=...,dtype=...}``), so the
        # tracing spine sees the sharded/bf16 engines through the
        # existing ``GET /v1/metrics`` endpoint.
        rungs: Dict[str, float] = {}
        drain_s = 0.0
        for r in replicas:
            m = r.scheduler.metrics
            snap = m.snapshot()
            healthy += int(r.healthy)
            merged.extend(m.latencies_snapshot())
            # Host-level backlog estimate: the sum of every replica's
            # drain time. Rides the mesh heartbeat as the gossip field
            # the MetaRouter routes on (serving/mesh/router.py) — the
            # same join-the-shortest-TIME-queue quantity the fleet
            # router uses per replica, aggregated per host.
            drain_s += float(r.scheduler.estimated_drain_s())
            out[f"replica{r.index}_routed"] = float(routed.get(r.index, 0))
            out[f"replica{r.index}_requests"] = snap["requests"]
            out[f"replica{r.index}_occupancy_pct"] = snap[
                "batch_occupancy_pct"
            ]
            out[f"replica{r.index}_queue_depth"] = snap["queue_depth"]
            out[f"replica{r.index}_healthy"] = float(r.healthy)
            out[f"replica{r.index}_batch_preempted_total"] = snap[
                "batch_preempted_total"
            ]
            engine = getattr(r, "engine", None)
            if engine is not None:
                dtype = getattr(engine, "dtype_label", "f32")
                is_sharded = bool(getattr(engine, "is_sharded", False))
                kind = "sharded" if is_sharded else "replicated"
                for bucket, count in engine.compile_counts().items():
                    prefix = f"rung{bucket}_{dtype}"
                    # "a mesh slice serves this (rung, dtype)" — kept
                    # per (rung, dtype) deliberately; WHICH engine kind
                    # compiled what is the kind-labeled gauge below
                    # (both kinds can serve the same rung, so folding
                    # compile counts across kinds would make a receipt
                    # breach unattributable).
                    rungs[f"{prefix}_sharded"] = max(
                        rungs.get(f"{prefix}_sharded", 0.0),
                        float(is_sharded),
                    )
                    ckey = f"{prefix}_{kind}_compiles"
                    rungs[ckey] = max(rungs.get(ckey, 0.0), float(count))
        out.update(rungs)
        out["fleet_healthy_replicas"] = float(healthy)
        out["fleet_estimated_drain_s"] = drain_s
        ordered = sorted(merged)
        pct = ServingMetrics._percentile
        out["latency_p50_ms"] = 1e3 * pct(ordered, 0.50)
        out["latency_p95_ms"] = 1e3 * pct(ordered, 0.95)
        out["latency_p99_ms"] = 1e3 * pct(ordered, 0.99)
        # Registry-backed emission (obs/metrics.py): every snapshot also
        # lands in the process-global registry, so the serving families
        # and the trainer/pipeline gauges render as ONE merged Prometheus
        # namespace (fleet ``GET /v1/metrics`` text view, the
        # TelemetryServer's ``GET /metrics``, and the RollbackMonitor's
        # sampling path all read the same numbers).
        get_registry().record_gauges(out)
        return out
