"""Fleet observability: the aggregate view a multi-replica server needs.

Per-replica ``ServingMetrics`` already exist (each scheduler owns one);
what the fleet layer adds is the numbers that only make sense ABOVE the
replicas:

- ``fleet_routed_total`` / per-replica routed counts — routing skew is
  the router's core behavior; a flat-lined replica under load means the
  drain estimator or the health state is wrong.
- ``fleet_failed_over_total`` — requests transparently re-routed off a
  dying replica. Nonzero during an incident is the system WORKING;
  nonzero in steady state means a replica is flapping.
- ``fleet_rejected_total`` — fleet-level backpressure: every healthy
  replica was full. This is the number capacity planning watches.
- ``fleet_breaks_total`` / ``fleet_healthy_replicas`` — circuit-breaker
  activity and the live serving width.
- merged ``latency_p50/p95/p99_ms`` — computed over the raw latency
  samples of every replica pooled together (averaging per-replica
  percentiles is statistically meaningless).

``snapshot(replicas)`` returns the flat ``{name: float}`` dict shape the
rest of the repo logs through ``utils.logging.MetricsLogger``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence

from marl_distributedformation_tpu.serving.metrics import ServingMetrics


class FleetMetrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.routed_total = 0
        self.rejected_total = 0
        self.failed_over_total = 0
        self.breaks_total = 0
        self.probes_total = 0
        self._routed_per_replica: Dict[int, int] = {}

    # -- recording (router side) ----------------------------------------

    def record_routed(self, replica: int) -> int:
        """Returns the new fleet-wide routed count (the router uses it
        to pace logger emission)."""
        with self._lock:
            self.routed_total += 1
            self._routed_per_replica[replica] = (
                self._routed_per_replica.get(replica, 0) + 1
            )
            return self.routed_total

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected_total += 1

    def record_failover(self) -> None:
        with self._lock:
            self.failed_over_total += 1

    def record_break(self) -> None:
        with self._lock:
            self.breaks_total += 1

    def record_probe(self) -> None:
        with self._lock:
            self.probes_total += 1

    # -- reading ---------------------------------------------------------

    def routed_per_replica(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._routed_per_replica)

    def snapshot(self, replicas: Sequence) -> Dict[str, float]:
        """Flat float dict over the fleet counters plus every replica's
        own metrics; ``replicas`` is the router's replica list (each
        exposes ``.index``, ``.healthy``, ``.scheduler.metrics``)."""
        with self._lock:
            out: Dict[str, float] = {
                "fleet_replicas": float(len(replicas)),
                "fleet_routed_total": float(self.routed_total),
                "fleet_rejected_total": float(self.rejected_total),
                "fleet_failed_over_total": float(self.failed_over_total),
                "fleet_breaks_total": float(self.breaks_total),
                "fleet_probes_total": float(self.probes_total),
            }
            routed = dict(self._routed_per_replica)
        merged: List[float] = []
        healthy = 0
        for r in replicas:
            m = r.scheduler.metrics
            snap = m.snapshot()
            healthy += int(r.healthy)
            merged.extend(m.latencies_snapshot())
            out[f"replica{r.index}_routed"] = float(routed.get(r.index, 0))
            out[f"replica{r.index}_requests"] = snap["requests"]
            out[f"replica{r.index}_occupancy_pct"] = snap[
                "batch_occupancy_pct"
            ]
            out[f"replica{r.index}_queue_depth"] = snap["queue_depth"]
            out[f"replica{r.index}_healthy"] = float(r.healthy)
        out["fleet_healthy_replicas"] = float(healthy)
        ordered = sorted(merged)
        pct = ServingMetrics._percentile
        out["latency_p50_ms"] = 1e3 * pct(ordered, 0.50)
        out["latency_p95_ms"] = 1e3 * pct(ordered, 0.95)
        out["latency_p99_ms"] = 1e3 * pct(ordered, 0.99)
        return out
