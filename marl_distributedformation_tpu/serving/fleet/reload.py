"""Fleet-wide coordinated hot reload: poll once, swap everywhere,
globally step-monotonic.

``ModelRegistry`` (serving/registry.py) solves hot reload for ONE
engine: snapshot-per-batch plus a step-monotonic swap under a lock. A
fleet of replicas re-raises the consistency question — if each replica
polled and swapped independently, two things go wrong: N replicas pay N
redundant restores per checkpoint, and (worse) a client hopping between
replicas can observe ``model_step`` going BACKWARD: replica A swaps to
step 200 and answers, then replica B — poll racing a slow restore —
answers with step 100. The ROADMAP names the fix: "coordinator polls,
broadcasts the step, hosts swap at a batch barrier".

:class:`FleetReloadCoordinator` implements exactly that:

1. **Poll once.** One watcher polls ``logs/{name}/`` via
   ``latest_checkpoint``; one restore + one validation per new
   checkpoint, regardless of fleet width.
2. **Prepare.** The validated host tree is ``device_put`` onto every
   replica's device BEFORE any replica is touched — no replica ever
   stalls mid-swap waiting for a weight upload.
3. **Commit at the fleet batch barrier.** Every replica's scheduler
   holds its registry's ``batch_lock`` for the duration of each
   dispatch (scheduler.py). The coordinator acquires ALL replica locks,
   which can only succeed at a moment when zero batches are in flight
   anywhere, flips every replica's ``(params, step)`` cell, and
   releases. Consequence: every response resolved before the commit
   carries the old step, every response dispatched after carries the
   new one — ``model_step`` is globally monotonic in response order,
   fleet-wide, with no pause longer than one in-flight batch.

Failure containment mirrors the single-engine registry: a
mismatched-architecture / drifted-dtype / foreign checkpoint is a
recorded ``load_errors`` entry and the fleet keeps serving the old
params; older/equal steps are ignored; broken replicas still receive
the new params so a later revival serves the current step, never a
stale one.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Deque, Optional, Tuple

from marl_distributedformation_tpu.chaos.plane import fault_point
from marl_distributedformation_tpu.obs import get_tracer
from marl_distributedformation_tpu.utils.checkpoint import (
    CheckpointDiscovery,
    checkpoint_step,
    latest_checkpoint,
    restore_state_dict_partial,
)


class BatchBarrier:
    """A dispatch lock with a coordinator-side gate.

    The worker side is a plain context manager held across each dispatch
    (``with registry.batch_lock:``). The subtlety is FAIRNESS: under
    load a worker releases its lock and re-acquires it microseconds
    later for the next batch, and CPython locks are not FIFO — a
    coordinator blocked in ``acquire()`` can starve for seconds behind
    that re-acquisition loop. So the coordinator first ``close()``s the
    gate; workers park at the gate BEFORE contending the lock, and the
    coordinator gets every lock within at most one in-flight batch.
    ``open()`` releases the parked workers after the commit.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._open = threading.Event()
        self._open.set()

    # -- worker side (one dispatch) --------------------------------------

    def __enter__(self) -> "BatchBarrier":
        self._open.wait()
        self._lock.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._lock.release()

    # -- coordinator side (fleet commit) ---------------------------------

    def close(self) -> None:
        self._open.clear()

    def acquire(self, timeout: Optional[float] = None) -> bool:
        return self._lock.acquire(
            timeout=-1 if timeout is None else timeout
        )

    def release(self) -> None:
        self._lock.release()

    def open(self) -> None:
        self._open.set()


class ReplicaRegistry:
    """One replica's ``(params, step)`` cell plus its batch barrier.

    The scheduler holds ``batch_lock`` across each dispatch and reads
    :meth:`active` once per micro-batch; the coordinator writes via
    :meth:`install` only while holding every replica's barrier.
    ``active`` itself is lock-free — a single tuple attribute read is
    atomic in CPython, and the worker already holds the barrier when it
    snapshots (a locking ``active`` would self-deadlock)."""

    def __init__(self, params: Any, step: int, device: Any = None) -> None:
        self.device = device
        self.batch_lock = BatchBarrier()  # graftlock: gate
        self.swap_count = 0  # graftlock: guarded-by=batch_lock
        self._snapshot: Tuple[Any, int] = (params, step)  # graftlock: guarded-by=batch_lock

    def active(self) -> Tuple[Any, int]:
        return self._snapshot

    @property
    def active_step(self) -> int:
        return self._snapshot[1]

    # graftlock: holds=batch_lock
    def install(self, params: Any, step: int) -> None:
        """Replace the serving snapshot. Caller holds ``batch_lock``."""
        self._snapshot = (params, step)
        self.swap_count += 1


class FleetReloadCoordinator:
    """Single poller + fleet-wide batch-barrier swap over a router.

    Args:
      log_dir: the ``logs/{name}/`` directory the trainer checkpoints to.
      router: a started-or-not ``fleet.FleetRouter``; the coordinator
        swaps through its replicas' :class:`ReplicaRegistry` cells.
      poll_interval_s: cadence of the background watcher (``start()``);
        ``refresh()`` may also be called directly.
      commit_timeout_s: bound on waiting for any single replica's
        barrier at commit time. A worker wedged inside a device dispatch
        (a hung tunnel op) holds its lock indefinitely; without the
        bound, one wedged replica would park the WHOLE fleet behind
        closed gates. On timeout the commit aborts cleanly — locks
        released, gates reopened, a recorded ``load_errors`` entry —
        and every replica keeps serving the old step (never a partial
        swap); the next poll retries.
      model_id: optional tenant lane (serving/tenancy): the coordinator
        then watches ONE lane's ``promoted/`` directory and commits
        into each replica's ``registries[model_id]`` cell, acquiring
        only that lane's batch barriers — other lanes' dispatch groups
        keep running through the whole commit, and ``fleet_step`` is
        that lane's own monotonic step (per-model monotonicity).
    """

    def __init__(
        self,
        log_dir: str | Path,
        router: Any,
        poll_interval_s: float = 2.0,
        max_recorded_errors: int = 32,
        commit_timeout_s: float = 30.0,
        model_id: Optional[str] = None,
    ) -> None:
        self.log_dir = Path(log_dir)
        self.router = router
        self.model_id = model_id
        self.poll_interval_s = poll_interval_s
        self.commit_timeout_s = commit_timeout_s
        self.swap_count = 0  # graftlock: guarded-by=_refresh_lock
        # Host-count/commit-round attribution of the newest landed swap
        # (promotions.jsonl schema 4). A single-host fleet always
        # commits 1 host; the mesh coordinator's global commit mirrors
        # this attribute with the real host count and round number.
        self.last_commit: Optional[dict] = None  # graftlock: guarded-by=_refresh_lock
        # Unannotated on purpose: deque.append is atomic under the GIL
        # and failure paths record without re-entering any lock.
        self.load_errors: Deque[Tuple[str, str]] = deque(
            maxlen=max_recorded_errors
        )
        # Cross-host staged state (prepare_global/commit_prepared): the
        # mesh coordinator's two-phase barrier holds this host paused —
        # gates closed, every replica barrier held, new params staged —
        # between the prepare ack and the commit/abort decision.
        self._staged: Optional[dict] = None  # graftlock: guarded-by=_staged_lock
        self._staged_lock = threading.Lock()
        # Incremental discovery: a long-running watcher polls this
        # directory forever, and re-listing + re-parsing every historic
        # checkpoint each poll degrades O(total checkpoints). Same
        # discovery contract as latest_checkpoint (utils.checkpoint).
        self._discovery = CheckpointDiscovery(self.log_dir)
        # The fleet step starts at the newest step any replica already
        # serves (the router seeds every replica identically).
        self._fleet_step = max(  # graftlock: guarded-by=_refresh_lock
            reg.active_step for reg in self._commit_registries()
        )
        self._refresh_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _commit_registries(self) -> list:
        """The registry cells this coordinator swaps — one per replica.
        Single-model: each replica's primary ``registry``. Lane-keyed
        (``model_id`` set): each replica's ``registries[model_id]``
        cell, whose barrier gates only that lane's dispatch groups."""
        if self.model_id is None:
            return [r.registry for r in self.router.replicas]
        return [
            r.registries[self.model_id] for r in self.router.replicas
        ]

    @property
    def fleet_step(self) -> int:
        """The step every post-commit dispatch serves (this lane's, when
        the coordinator is lane-keyed)."""
        return self._fleet_step

    # -- reload ---------------------------------------------------------

    def refresh(self, trace_id: Optional[str] = None) -> bool:
        """Check the directory once; coordinated-swap if a newer
        checkpoint landed. Returns True on swap. Load failures keep the
        old params serving fleet-wide and are recorded. ``trace_id``
        labels the commit's spans (the pipeline passes its candidate's
        ID so one trace reconstructs the whole promotion)."""
        with self._refresh_lock:
            path = self._discovery.latest()
            if path is None:
                return False
            step = checkpoint_step(path)
            if step <= self._fleet_step:
                return False
            return self._load_and_commit(path, step, trace_id)

    def reload_pinned(
        self,
        path: str | Path,
        monotonic: bool = True,
        trace_id: Optional[str] = None,
    ) -> bool:
        """Coordinated swap of an EXPLICIT checkpoint path, bypassing
        directory discovery. ``monotonic=False`` is the DEMOTION hook
        (pipeline/rollback): the swap is exempt from the never-go-
        backward rule, so a rollback to the last-good checkpoint is just
        a pinned reload at the same fleet batch barrier — responses
        after the commit legitimately carry the older step, and the
        caller owns retracting the demoted checkpoint from the watched
        directory (otherwise the next poll would re-promote it). With
        ``monotonic=True`` this is a targeted forward swap with the
        usual old-steps-ignored semantics. Same containment contract as
        :meth:`refresh`: a bad file is a recorded ``load_errors`` entry
        and the fleet keeps serving what it serves."""
        path = Path(path)
        with self._refresh_lock:
            try:
                step = checkpoint_step(path)
            except ValueError as e:
                self.load_errors.append((str(path), repr(e)))
                return False
            if monotonic and step <= self._fleet_step:
                return False
            if step == self._fleet_step:
                return False  # already serving exactly this step
            return self._load_and_commit(path, step, trace_id)

    # graftlock: holds=_refresh_lock
    def _load_and_commit(
        self, path: Path, step: int, trace_id: Optional[str] = None
    ) -> bool:
        """Restore + validate once, then commit fleet-wide at the batch
        barrier. Caller holds ``_refresh_lock``."""
        tracer = get_tracer()
        try:
            with tracer.span(
                "reload.load", trace_id=trace_id, step=step, path=str(path)
            ):
                restored = self._load_validated(path)
        except Exception as e:  # noqa: BLE001 — serving must not die
            self.load_errors.append((str(path), repr(e)))
            return False
        import jax

        # Prepare: one host->device upload per replica, all before
        # the barrier — the commit window stays lock-acquisition
        # plus pointer flips, never a weight transfer.
        with tracer.span("reload.stage", trace_id=trace_id, step=step):
            staged = [
                (reg, jax.device_put(restored, reg.device))
                for reg in self._commit_registries()
            ]
        barriers = [reg.batch_lock for reg, _ in staged]
        held = []
        installed = []
        wedged_replica = None
        try:
            # Close every gate FIRST: workers finish their current
            # batch and park instead of re-contending their lock, so
            # the acquisitions below complete within one in-flight
            # batch (BatchBarrier's fairness note). Workers only
            # ever hold their own lock — no cycle to deadlock on.
            # With all locks held, zero batches are in flight
            # fleet-wide: the commit point. The per-barrier timeout
            # bounds a wedged replica (hung device op holding its
            # lock): abort the WHOLE commit rather than park the
            # fleet or swap partially — the finally reopens every
            # gate and the old step keeps serving everywhere.
            for b in barriers:
                b.close()
            for i, b in enumerate(barriers):
                fault_point("fleet.barrier")
                t_acq = time.perf_counter()
                acquired = b.acquire(timeout=self.commit_timeout_s)
                tracer.add_span(
                    "reload.barrier_acquire",
                    t_acq,
                    time.perf_counter(),
                    trace_id=trace_id,
                    replica=i,
                    acquired=acquired,
                )
                if not acquired:
                    self.load_errors.append(
                        (
                            str(path),
                            f"commit aborted: replica {i} barrier "
                            f"not acquired in {self.commit_timeout_s}"
                            "s (wedged dispatch?); old step keeps "
                            "serving fleet-wide",
                        )
                    )
                    wedged_replica = i
                    return False
                held.append(b)
            with tracer.span(
                "reload.commit", trace_id=trace_id, step=step,
                replicas=len(staged),
            ):
                for reg, params in staged:
                    prev = reg.active()
                    fault_point("registry.swap")
                    reg.install(params, step)
                    installed.append((reg, prev))
                self._fleet_step = step
                self.swap_count += 1
                self.last_commit = {
                    "commit_round": self.swap_count,
                    "host_count": 1,
                    "step": step,
                }
                if self.model_id is not None:
                    self.last_commit["model_id"] = self.model_id
        except Exception as e:  # noqa: BLE001 — contain + untear
            # A failure mid-commit (an injected fault, a broken
            # registry) must not leave a TORN swap: some replicas on
            # the new step, others on the old, is exactly the
            # inconsistency the batch barrier exists to prevent. Roll
            # every installed replica back to its previous cell (all
            # locks are still held — the fleet never serves the torn
            # state), record, and keep serving the old step everywhere.
            for reg, (prev_params, prev_step) in reversed(installed):
                reg.install(prev_params, prev_step)
            self.load_errors.append(
                (
                    str(path),
                    f"commit aborted mid-swap and rolled back: {e!r}; "
                    "old step keeps serving fleet-wide",
                )
            )
            return False
        finally:
            for b in reversed(held):
                b.release()
            for b in barriers:
                b.open()
            if wedged_replica is not None:
                # A wedged barrier is a postmortem-grade incident: the
                # ring still holds the dispatches that led here. Dumped
                # AFTER the gates reopen — the flight-recorder file
                # write must not extend the fleet-wide serving pause.
                tracer.incident(
                    "wedged_barrier_abort",
                    trace_id=trace_id,
                    replica=wedged_replica,
                    step=step,
                    path=str(path),
                    commit_timeout_s=self.commit_timeout_s,
                )
        # Swap boundary: both param generations are still referenced
        # here (staged + the replicas' previous cells), which is the
        # transient double-residency peak the autoscaler must plan for —
        # sample it into the ledger's watermark gauge AFTER the gates
        # reopened, so the reading never extends the serving pause.
        from marl_distributedformation_tpu.analysis.guards import (
            sample_device_watermark,
        )

        sample_device_watermark(force=True)  # swaps are rare: always sample
        return True

    def _load_validated(self, path: Path) -> Any:
        """One restore + validation for the whole fleet, against replica
        0's live tree (all replicas serve the same architecture) — the
        same template validation ``ModelRegistry.refresh`` performs."""
        from marl_distributedformation_tpu.compat.policy import (
            load_checkpoint_raw,
        )

        raw = load_checkpoint_raw(path)
        want = type(self.router.policy.model).__name__
        got = raw.get("policy", want)
        if got != want:
            raise ValueError(
                f"checkpoint {path} was trained with policy {got!r}; "
                f"this fleet serves {want!r}"
            )
        template = {"params": self._commit_registries()[0].active()[0]}
        return restore_state_dict_partial(
            raw, template, origin=str(path)
        )["params"]

    # -- elastic re-split (serving/elastic) ------------------------------

    def commit_resplit(
        self,
        add: Any = (),
        retire: Any = (),
        sharded_min_rows: Optional[int] = None,
        trace_id: Optional[str] = None,
    ) -> dict:
        """Land a capacity re-split — replicas added, replicas retired,
        the big-rung routing threshold re-pinned — at the SAME fleet
        batch barrier a reload commits at, so no in-flight request ever
        observes a torn replica set and ``model_step`` monotonicity is
        untouched (added replicas must already serve the current fleet
        step; a prewarm the fleet stepped past is refused, the
        controller retries).

        ``add`` replicas come PREWARMED from the controller: engines
        built, every rung compiled off the serving path, schedulers
        started but unrouted. ``retire`` names replica indices to swap
        out of routing; the CALLER drains and stops them after the
        gates reopen (``router.drain_replica`` — drain-before-retire
        must not extend the serving pause).

        Returns a report dict; never raises. ``committed`` False means
        the old split keeps serving and ``load_errors`` records why.
        ``pause_ms`` is the barrier-commit pause only — gates closed to
        gates reopened — which is the whole serving interruption a
        re-split costs (prewarm compiles happen before, drains after).
        """
        if self.model_id is not None:
            raise ValueError(
                "elastic re-split over a lane-keyed coordinator is not "
                "supported yet (docs/serving.md 'Limits / next')"
            )
        add = list(add)
        retire_set = {int(i) for i in retire}
        tracer = get_tracer()
        report: dict = {
            "committed": False,
            "pause_ms": 0.0,
            "added": [r.index for r in add],
            "retired": sorted(retire_set),
        }
        with self._refresh_lock:
            current = list(self.router.replicas)
            known = {r.index for r in current}
            missing = retire_set - known
            if missing:
                self.load_errors.append(
                    (
                        "resplit",
                        f"resplit refused: retire names unknown "
                        f"replicas {sorted(missing)}",
                    )
                )
                return report
            stale = [
                r.index
                for r in add
                if r.registry.active_step != self._fleet_step
            ]
            if stale:
                # The fleet stepped forward while the controller was
                # prewarming: committing these replicas would serve an
                # older step after a newer one — exactly the
                # monotonicity violation the barrier exists to prevent.
                self.load_errors.append(
                    (
                        "resplit",
                        f"resplit refused: prewarmed replicas {stale} "
                        f"serve a step != fleet step {self._fleet_step} "
                        "(reload landed during prewarm); re-prewarm and "
                        "retry",
                    )
                )
                report["stale_prewarm"] = True
                return report
            barriers = [r.registry.batch_lock for r in current]
            held = []
            wedged_replica = None
            t_closed = 0.0
            t_open = 0.0
            try:
                for b in barriers:
                    b.close()
                t_closed = time.perf_counter()
                for i, b in enumerate(barriers):
                    fault_point("fleet.barrier")
                    acquired = b.acquire(timeout=self.commit_timeout_s)
                    if not acquired:
                        self.load_errors.append(
                            (
                                "resplit",
                                f"resplit aborted: replica {i} barrier "
                                f"not acquired in {self.commit_timeout_s}"
                                "s (wedged dispatch?); old split keeps "
                                "serving",
                            )
                        )
                        wedged_replica = i
                        return report
                    held.append(b)
                with tracer.span(
                    "elastic.commit",
                    trace_id=trace_id,
                    added=len(add),
                    retired=len(retire_set),
                ):
                    fault_point("elastic.commit")
                    self.router._commit_resplit(
                        add, retire_set, sharded_min_rows=sharded_min_rows
                    )
                    report["committed"] = True
                    report["step"] = self._fleet_step
            except Exception as e:  # noqa: BLE001 — contain, keep serving
                # The membership swap is one list assignment — a fault
                # before it (the armed elastic.commit seam) leaves the
                # old split fully intact; nothing to untear.
                self.load_errors.append(
                    (
                        "resplit",
                        f"resplit commit aborted: {e!r}; old split "
                        "keeps serving",
                    )
                )
                report["error"] = repr(e)
                return report
            finally:
                for b in reversed(held):
                    b.release()
                for b in barriers:
                    b.open()
                t_open = time.perf_counter()
                report["pause_ms"] = round(
                    max(0.0, (t_open - t_closed)) * 1e3, 3
                )
                if wedged_replica is not None:
                    tracer.incident(
                        "wedged_barrier_abort",
                        trace_id=trace_id,
                        replica=wedged_replica,
                        step=self._fleet_step,
                        path="resplit",
                        commit_timeout_s=self.commit_timeout_s,
                    )
        # Both the retiring and the incoming engines' params are live
        # here — the same double-residency shape a reload peaks at.
        # Sample AFTER the gates reopened: the watermark read must not
        # extend the pause it is measuring.
        from marl_distributedformation_tpu.analysis.guards import (
            sample_device_watermark,
        )

        sample_device_watermark(force=True)
        return report

    # -- cross-host staged two-phase (serving/mesh) ----------------------
    #
    # The mesh coordinator generalizes the batch-barrier commit across
    # hosts: it cannot hold every host's locks itself, so each host
    # splits _load_and_commit at the commit point. ``prepare_global``
    # does everything UP TO the pointer flip — restore + validate once,
    # stage per-replica uploads, close the gates, acquire every replica
    # barrier — then HOLDS that state (the host serves nothing) until
    # the coordinator decides: ``commit_prepared`` flips every cell and
    # resumes, ``abort_prepared`` resumes on the old step. Because every
    # host pauses before any host commits, no old-step response can
    # complete after a new-step response anywhere — model_step stays
    # globally monotonic in response completion order across the mesh.
    # ``ttl_s`` bounds an orphaned prepare (coordinator died mid-round):
    # the host auto-aborts and keeps serving the old step rather than
    # staying paused forever.

    def prepare_global(
        self,
        path: str | Path,
        step: Optional[int] = None,
        monotonic: bool = True,
        trace_id: Optional[str] = None,
        ttl_s: Optional[float] = 60.0,
    ) -> Tuple[bool, str]:
        """Phase 1 of the cross-host swap: stage + pause. Returns
        ``(staged, reason)``; on False the host is untouched and keeps
        serving. The refresh lock stays held across a successful
        prepare so no local reload can interleave with the mesh round —
        commit/abort release it."""
        path = Path(path)
        # Refuse FAST when the lock is busy instead of parking: the
        # refresh lock is only held long while a round is staged, and
        # a prepare that blocks past the coordinator's RPC timeout
        # becomes a zombie — its late "staged" ack lands after the
        # round aborted, wedging the NEXT round in turn. A quick typed
        # refusal lets the coordinator abort-and-clear and retry.
        if not self._refresh_lock.acquire(timeout=0.25):
            with self._staged_lock:
                staleness = (
                    f" (round {self._staged['round_tag']} is staged "
                    "here awaiting commit/abort)"
                    if self._staged is not None
                    else ""
                )
            return False, f"another reload holds the refresh lock{staleness}"
        staged_ok = False
        try:
            with self._staged_lock:
                if self._staged is not None:
                    return False, (
                        f"round {self._staged['round_tag']} is already "
                        "staged on this host (commit or abort it first)"
                    )
            try:
                step = checkpoint_step(path) if step is None else int(step)
            except ValueError as e:
                self.load_errors.append((str(path), repr(e)))
                return False, f"unparseable checkpoint name: {e}"
            if monotonic and step <= self._fleet_step:
                return False, (
                    f"stale step {step} <= served {self._fleet_step}"
                )
            if step == self._fleet_step:
                return False, f"already serving step {step}"
            tracer = get_tracer()
            try:
                with tracer.span(
                    "reload.load", trace_id=trace_id, step=step,
                    path=str(path),
                ):
                    restored = self._load_validated(path)
            except Exception as e:  # noqa: BLE001 — serving must not die
                self.load_errors.append((str(path), repr(e)))
                return False, f"load failed: {e!r}"
            import jax

            with tracer.span(
                "reload.stage", trace_id=trace_id, step=step
            ):
                staged = [
                    (reg, jax.device_put(restored, reg.device))
                    for reg in self._commit_registries()
                ]
            barriers = [reg.batch_lock for reg, _ in staged]
            held = []
            wedged_replica = None
            try:
                for b in barriers:
                    b.close()
                for i, b in enumerate(barriers):
                    fault_point("fleet.barrier")
                    t_acq = time.perf_counter()
                    acquired = b.acquire(timeout=self.commit_timeout_s)
                    tracer.add_span(
                        "reload.barrier_acquire",
                        t_acq,
                        time.perf_counter(),
                        trace_id=trace_id,
                        replica=i,
                        acquired=acquired,
                    )
                    if not acquired:
                        reason = (
                            f"prepare aborted: replica {i} barrier not "
                            f"acquired in {self.commit_timeout_s}s "
                            "(wedged dispatch?); old step keeps serving"
                        )
                        self.load_errors.append((str(path), reason))
                        wedged_replica = i
                        return False, reason
                    held.append(b)
            except BaseException as e:
                # Untear like _load_and_commit: an exception with gates
                # closed (an armed fleet.barrier fault, a broken
                # registry) must not leave the host paused forever —
                # the only finally below releases the refresh lock,
                # not these.
                reason = f"prepare aborted mid-acquisition: {e!r}"
                self.load_errors.append((str(path), reason))
                if isinstance(e, Exception):
                    return False, reason
                raise  # SimulatedCrash-grade: die, but gates reopened
            finally:
                landed = len(held) == len(barriers)
                if not landed:
                    for h in reversed(held):
                        h.release()
                    for b in barriers:
                        b.open()
                if wedged_replica is not None:
                    # Postmortem dump AFTER the partial acquisitions
                    # released and the gates reopened — mirroring
                    # _load_and_commit, the flight-recorder file write
                    # must not extend the serving pause the wedged
                    # barrier already caused.
                    tracer.incident(
                        "wedged_barrier_abort",
                        trace_id=trace_id,
                        replica=wedged_replica,
                        step=step,
                        path=str(path),
                        commit_timeout_s=self.commit_timeout_s,
                    )
            timer: Optional[threading.Timer] = None
            entry = {
                "round_tag": f"step{step}",
                "path": path,
                "step": step,
                "staged": staged,
                "barriers": barriers,
                "held": held,
                "trace_id": trace_id,
                "timer": None,
            }
            if ttl_s is not None:
                timer = threading.Timer(
                    ttl_s, self._ttl_abort, args=(entry,)
                )
                timer.daemon = True
                entry["timer"] = timer
            with self._staged_lock:
                self._staged = entry
            if timer is not None:
                timer.start()
            staged_ok = True
            return True, f"staged step {step}"
        finally:
            if not staged_ok:
                self._refresh_lock.release()

    def _take_staged(self) -> Optional[dict]:
        with self._staged_lock:
            entry, self._staged = self._staged, None
        if entry is not None and entry["timer"] is not None:
            entry["timer"].cancel()
        return entry

    # graftlock: holds=_refresh_lock
    def commit_prepared(self, trace_id: Optional[str] = None) -> bool:
        """Phase 2: flip every staged replica and resume. Returns False
        when nothing is staged (an aborted/TTL-expired round — the
        coordinator treats that as this host having dropped out).
        The refresh lock was acquired by :meth:`prepare_global` and is
        released here (or by abort) — the staged window holds it."""
        entry = self._take_staged()
        if entry is None:
            return False
        tracer = get_tracer()
        installed = []
        try:
            with tracer.span(
                "reload.commit",
                trace_id=trace_id or entry["trace_id"],
                step=entry["step"],
                replicas=len(entry["staged"]),
            ):
                for reg, params in entry["staged"]:
                    prev = reg.active()
                    fault_point("registry.swap")
                    reg.install(params, entry["step"])
                    installed.append((reg, prev))
                self._fleet_step = entry["step"]
                self.swap_count += 1
        except Exception as e:  # noqa: BLE001 — contain + untear
            for reg, (prev_params, prev_step) in reversed(installed):
                reg.install(prev_params, prev_step)
            self.load_errors.append(
                (
                    str(entry["path"]),
                    f"staged commit aborted mid-swap and rolled back: "
                    f"{e!r}; old step keeps serving",
                )
            )
            return False
        finally:
            for b in reversed(entry["held"]):
                b.release()
            for b in entry["barriers"]:
                b.open()
            self._refresh_lock.release()
        from marl_distributedformation_tpu.analysis.guards import (
            sample_device_watermark,
        )

        sample_device_watermark(force=True)
        return True

    def abort_prepared(self, reason: str = "") -> bool:
        """Resume on the old step without installing anything (the
        coordinator's round failed on some other host, or the local
        TTL expired). Always safe to call; returns False when nothing
        was staged."""
        entry = self._take_staged()
        if entry is None:
            return False
        for b in reversed(entry["held"]):
            b.release()
        for b in entry["barriers"]:
            b.open()
        self._refresh_lock.release()
        if reason:
            self.load_errors.append(
                (str(entry["path"]), f"prepare aborted: {reason}")
            )
        return True

    def _ttl_abort(self, entry: dict) -> None:
        """An orphaned prepare (no commit/abort before the TTL): the
        coordinator is gone — resume serving the OLD step rather than
        stay paused forever. Guarded against racing a landing commit:
        only fires if this exact entry is still the staged one."""
        with self._staged_lock:
            if self._staged is not entry:
                return  # commit/abort won the race
        self.abort_prepared(
            "prepare TTL expired with no commit/abort — coordinator "
            "presumed dead; serving resumed on the old step"
        )
        get_tracer().incident(
            "orphaned_prepare_abort",
            trace_id=entry["trace_id"],
            step=entry["step"],
            path=str(entry["path"]),
        )

    # -- background watcher ---------------------------------------------

    def start(self) -> "FleetReloadCoordinator":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, name="fleet-reload-coordinator", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10.0)
        self._thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self.refresh()

    def __enter__(self) -> "FleetReloadCoordinator":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def fleet_from_checkpoint_dir(
    log_dir: str | Path,
    env_params: Any = None,
    act_dim: int = 2,
    poll_interval_s: float = 2.0,
    **router_kwargs: Any,
):
    """Build a ``(FleetRouter, FleetReloadCoordinator)`` pair serving the
    newest checkpoint under ``log_dir`` — the fleet twin of constructing
    a ``ModelRegistry`` from a directory. Router kwargs (``buckets``,
    ``num_replicas``, ``window_ms``, …) pass through."""
    from marl_distributedformation_tpu.compat.policy import LoadedPolicy
    from marl_distributedformation_tpu.serving.fleet.router import (
        FleetRouter,
    )

    log_dir = Path(log_dir)
    path = latest_checkpoint(log_dir)
    if path is None:
        raise FileNotFoundError(
            f"no rl_model_*_steps.msgpack checkpoint under {log_dir} "
            "to serve"
        )
    policy = LoadedPolicy.from_checkpoint(
        path, act_dim=act_dim, env_params=env_params
    )
    router = FleetRouter(
        policy, initial_step=checkpoint_step(path), **router_kwargs
    )
    coordinator = FleetReloadCoordinator(
        log_dir, router, poll_interval_s=poll_interval_s
    )
    return router, coordinator
