"""Stdlib-only HTTP frontend: the network door, strictly above the fleet.

The compiled path must never learn about sockets — the frontend's whole
job is translating HTTP+JSON to ``FleetRouter.submit`` and the router's
failure taxonomy to status codes. ``http.server.ThreadingHTTPServer``
(one thread per connection, stdlib) is plenty: the per-request work here
is JSON parsing and a future wait; throughput lives below, in the
coalescing scheduler and the compiled engines, exactly where TF-Agents
(arXiv:1709.02878) says it belongs.

Protocol (all bodies JSON):

- ``POST /v1/act`` with ``{"obs": [[...row...], ...],
  "deterministic": true, "timeout_s": 5.0,
  "slo_class": "interactive", "model_id": "lane-a"}`` →
  ``200 {"actions": [...], "model_step": N, "replica": i,
  "latency_s": x, "model_id": "lane-a"}``. ``model_step`` rides on
  every response — the fleet's version-pinning contract, end to end.
  ``slo_class`` (optional, default "interactive") is the admission
  class: "batch" traffic yields to interactive under backpressure
  (scheduler SLO classes — it dispatches behind queued interactive
  work and may be preempted with a 429 when an interactive request
  needs its slot). ``model_id`` names the tenant lane
  (serving/tenancy) — required by multi-tenant routers, rejected
  (400) by single-model ones — and is stamped on EVERY act response,
  success and failure alike, so a client juggling lanes can always
  attribute an answer (or a 429) to the lane that produced it.
- Backpressure → ``429`` with ``{"error": "backpressure",
  "retry_after_s": x}`` AND a standard ``Retry-After`` header (integer
  ceiling), so both JSON-aware clients and off-the-shelf HTTP retry
  middleware see the hint.
- Whole fleet broken → ``503``; request deadline passed → ``504``;
  malformed body/shape → ``400``. Unexpected server errors → ``500``
  with the exception class name (no tracebacks over the wire).
- ``GET /v1/health`` → ``200`` while any replica serves, ``503`` when
  none does (load-balancer shaped). ``GET /v1/metrics`` → the
  aggregated fleet snapshot — JSON by default, Prometheus text
  exposition when the client asks for it (``Accept: text/plain`` or an
  openmetrics type; ``obs/export.py``).
- **Trace identity**: ``POST /v1/act`` accepts an ``X-Trace-Id`` header
  (minting one when absent, sanitizing what arrives) and echoes it on
  EVERY response — success and failure alike — both as the header and
  as ``trace_id`` in the JSON body of errors (429/504/...), so a
  client's retries stay correlatable across failover. The same ID rides
  ``FleetRouter.submit`` down to the dispatch batch span (obs/).
"""

from __future__ import annotations

import json
import math
import threading

# On Python 3.10 (this project's floor) concurrent.futures.TimeoutError
# is NOT the builtin TimeoutError (they merged in 3.11) — catching only
# the builtin would turn a wedged-worker wait into a 500.
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np

from marl_distributedformation_tpu.chaos.plane import fault_point
from marl_distributedformation_tpu.obs import (
    PROMETHEUS_CONTENT_TYPE,
    TRACE_HEADER,
    new_trace_id,
    prometheus_exposition,
    sanitize_trace_id,
    wants_prometheus,
)
from marl_distributedformation_tpu.serving.fleet.router import (
    FleetRouter,
    NoHealthyReplicas,
)
from marl_distributedformation_tpu.serving.scheduler import (
    BackpressureError,
    RequestTimeout,
    SchedulerStopped,
)

MAX_BODY_BYTES = 64 * 1024 * 1024  # one request can't OOM the frontend


def _make_handler(router: FleetRouter):
    class _Handler(BaseHTTPRequestHandler):
        # The default handler logs one stderr line per request — at
        # serving rates that is an accidental hot-loop host sync of the
        # logging kind. Observability lives in /v1/metrics instead.
        def log_message(self, fmt: str, *args: Any) -> None:
            pass

        def _reply(
            self,
            status: int,
            payload: dict,
            retry_after_s: Optional[float] = None,
            trace_id: Optional[str] = None,
        ) -> None:
            # One seam for the 'every response correlates' contract:
            # the ID rides both the header and the JSON body.
            if trace_id is not None:
                payload = {**payload, "trace_id": trace_id}
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if trace_id is not None:
                self.send_header(TRACE_HEADER, trace_id)
            if retry_after_s is not None:
                self.send_header(
                    "Retry-After", str(max(1, math.ceil(retry_after_s)))
                )
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client gave up; nothing to salvage

        def _reply_text(
            self, status: int, text: str, content_type: str
        ) -> None:
            body = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass

        # -- reads -------------------------------------------------------

        def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path == "/v1/health":
                healthy = router.healthy_replicas
                payload = {
                    "healthy_replicas": healthy,
                    "replicas": len(router.replicas),
                    "model_step": int(
                        max(
                            r.registry.active_step
                            for r in router.replicas
                        )
                    ),
                }
                if getattr(router, "lane_ids", ()):
                    # Tenant lanes: per-model steps (each monotonic on
                    # its own), and model_step is the newest any lane
                    # serves.
                    steps = router.lane_steps()
                    payload["model_steps"] = {
                        mid: int(s) for mid, s in steps.items()
                    }
                    payload["model_step"] = int(max(steps.values()))
                self._reply(200 if healthy else 503, payload)
            elif self.path == "/v1/metrics":
                snap = router.snapshot()
                if wants_prometheus(self.headers.get("Accept")):
                    # The Prometheus view is the MERGED namespace: the
                    # process registry (trainer/pipeline/checkpoint
                    # gauges, when co-resident) plus the program
                    # ledger's per-executable families plus this
                    # fleet's own families; the fleet's keys win on
                    # overlap. The JSON default stays byte-identical
                    # to the router snapshot.
                    from marl_distributedformation_tpu.obs.ledger import (
                        merge_ledger_snapshot,
                    )
                    from marl_distributedformation_tpu.obs.metrics import (
                        get_registry,
                    )

                    merged = merge_ledger_snapshot(
                        get_registry().snapshot()
                    )
                    merged.update(snap)
                    self._reply_text(
                        200,
                        prometheus_exposition(merged),
                        PROMETHEUS_CONTENT_TYPE,
                    )
                else:
                    self._reply(200, snap)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        # -- act ---------------------------------------------------------

        def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
            # Trace identity first: accepted from the client (sanitized)
            # or minted here, echoed on EVERY response below — success,
            # backpressure, timeout — and carried through the router so
            # the dispatch batch span links back to this request.
            trace_id = (
                sanitize_trace_id(self.headers.get(TRACE_HEADER))
                or new_trace_id()
            )
            try:
                # Chaos seam: an injected handler fault degrades to a
                # typed 500 (the client's retry loop owns it) — never a
                # dropped connection, never a dead frontend thread.
                fault_point("frontend.handler")
            except Exception as e:  # noqa: BLE001 — injected by design
                self._reply(
                    500,
                    {"error": f"injected fault: {e}"},
                    trace_id=trace_id,
                )
                return
            if self.path != "/v1/act":
                self._reply(
                    404,
                    {"error": f"unknown path {self.path}"},
                    trace_id=trace_id,
                )
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                if not 0 < length <= MAX_BODY_BYTES:
                    raise ValueError(
                        f"Content-Length must be in (0, {MAX_BODY_BYTES}]"
                    )
                req = json.loads(self.rfile.read(length))
                obs = np.asarray(req["obs"], np.float32)
                deterministic = bool(req.get("deterministic", True))
                timeout_s = req.get("timeout_s")
                if timeout_s is not None:
                    timeout_s = float(timeout_s)
                slo_class = str(req.get("slo_class", "interactive"))
                if slo_class not in ("interactive", "batch"):
                    raise ValueError(
                        f"slo_class must be 'interactive' or 'batch', "
                        f"got {slo_class!r}"
                    )
                model_id = req.get("model_id")
                if model_id is not None:
                    model_id = str(model_id)
            except (ValueError, KeyError, TypeError) as e:
                self._reply(
                    400,
                    {"error": f"bad request: {e}"},
                    trace_id=trace_id,
                )
                return

            def _stamp(payload: dict) -> dict:
                # The lane rides EVERY act response (tenancy contract),
                # null in single-model mode.
                return {**payload, "model_id": model_id}

            try:
                future = router.submit(
                    obs, deterministic=deterministic, timeout_s=timeout_s,
                    trace_id=trace_id, slo_class=slo_class,
                    model_id=model_id,
                )
                wait = (
                    timeout_s
                    if timeout_s is not None
                    else router.default_timeout_s
                )
                # Failover can legitimately re-queue once; leave slack
                # beyond the request's own deadline (the scheduler
                # expires it itself) before declaring the server wedged.
                result = future.result(timeout=wait + 10.0)
            except BackpressureError as e:
                self._reply(
                    429,
                    _stamp({
                        "error": "backpressure",
                        "retry_after_s": e.retry_after_s,
                    }),
                    retry_after_s=e.retry_after_s,
                    trace_id=trace_id,
                )
            except NoHealthyReplicas as e:
                self._reply(
                    503,
                    _stamp({"error": str(e)}),
                    trace_id=trace_id,
                )
            except (RequestTimeout, TimeoutError, FutureTimeoutError) as e:
                self._reply(
                    504,
                    _stamp({"error": f"deadline passed: {e}"}),
                    trace_id=trace_id,
                )
            except SchedulerStopped as e:
                self._reply(
                    503,
                    _stamp({"error": str(e)}),
                    trace_id=trace_id,
                )
            except ValueError as e:
                self._reply(
                    400,
                    _stamp({"error": f"bad request: {e}"}),
                    trace_id=trace_id,
                )
            except Exception as e:  # noqa: BLE001 — no tracebacks on the wire
                self._reply(
                    500,
                    _stamp({"error": type(e).__name__}),
                    trace_id=trace_id,
                )
            else:
                self._reply(
                    200,
                    {
                        "actions": np.asarray(result.actions).tolist(),
                        "model_step": int(result.model_step),
                        "replica": int(result.replica),
                        "latency_s": round(result.latency_s, 6),
                        # The lane that ANSWERED (scheduler-stamped) —
                        # matches the request's lane by construction.
                        "model_id": result.model_id,
                    },
                    trace_id=trace_id,
                )

    return _Handler


class FleetFrontend:
    """Threaded HTTP server over a router; ``port=0`` binds ephemeral
    (the bound port is ``self.port`` — tests and the CLI print it)."""

    def __init__(
        self,
        router: FleetRouter,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.router = router
        self.server = ThreadingHTTPServer(
            (host, port), _make_handler(router)
        )
        self.server.daemon_threads = True
        self.host = self.server.server_address[0]
        self.port = self.server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "FleetFrontend":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self.server.serve_forever,
            name="fleet-frontend",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self.server.shutdown()
        self.server.server_close()
        self._thread.join(timeout=10.0)
        self._thread = None

    def __enter__(self) -> "FleetFrontend":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
