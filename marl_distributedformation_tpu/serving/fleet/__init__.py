"""Replica-managed serving fleet over the single-engine stack.

One ``MicroBatchScheduler`` + ``BucketedPolicyEngine`` pair serves one
device; this package scales that proven unit sideways, the Podracer way
(arXiv:2104.06272): replicate the compiled program per device behind a
thin host-side dispatch layer, and keep the network strictly outside
the compiled path.

- :class:`~.router.FleetRouter` — owns one replica per local device,
  routes each request to the healthy replica with the lowest estimated
  drain time, circuit-breaks replicas whose worker dies or whose
  RetraceGuard trips (with transparent failover of their accepted
  requests), and half-open-probes broken replicas back in.
- :class:`~.reload.FleetReloadCoordinator` — polls the checkpoint
  directory ONCE for the whole fleet and swaps every replica at a
  fleet-wide batch barrier, so ``model_step`` in responses is globally
  monotonic (reload.py's module docstring is the consistency story).
- :class:`~.frontend.FleetFrontend` — stdlib-only HTTP/JSON frontend
  above ``FleetRouter.submit``: ``model_step`` on every response,
  ``429`` + ``Retry-After`` backpressure, load-balancer-shaped
  ``/v1/health``.
- :class:`~.metrics.FleetMetrics` — routed/rejected/failed-over/breaks
  counters plus merged-latency percentiles and per-replica occupancy,
  through the same ``MetricsLogger`` pipeline as everything else.
- :func:`~.smoke.run_fleet_smoke` — mixed-size request storm across the
  fleet with the acceptance receipts (compile counts per replica,
  global step-monotonicity violations) in the report.

Topology, failure modes, and the consistency model are documented in
``docs/serving.md`` ("Fleet").
"""

from marl_distributedformation_tpu.serving.fleet.frontend import (
    FleetFrontend,
)
from marl_distributedformation_tpu.serving.fleet.metrics import FleetMetrics
from marl_distributedformation_tpu.serving.fleet.reload import (
    FleetReloadCoordinator,
    ReplicaRegistry,
    fleet_from_checkpoint_dir,
)
from marl_distributedformation_tpu.serving.fleet.router import (
    FleetRouter,
    NoHealthyReplicas,
    Replica,
)
from marl_distributedformation_tpu.serving.fleet.smoke import (
    run_fleet_smoke,
    warmup_fleet,
)

__all__ = [
    "FleetFrontend",
    "FleetMetrics",
    "FleetReloadCoordinator",
    "FleetRouter",
    "NoHealthyReplicas",
    "Replica",
    "ReplicaRegistry",
    "fleet_from_checkpoint_dir",
    "run_fleet_smoke",
    "warmup_fleet",
]
