"""Candidate-seed populations of the heterogeneous curriculum in ONE jit.

Why this exists (round 5): deterministic-mode quality of the config-5
curriculum is SEED-VARIANT — the CPU study behind
docs/acceptance/hetero5/README.md measured only ~1/3-1/2 of seeds
producing a mode action that beats the scripted baseline in every eval
row, and a same-seed retrain is deterministic, so the chip acceptance
workflow was train-one-candidate -> det-gate -> reseed, one tunnel
window per candidate. This trainer collapses that loop: K candidate
seeds of the FULL curriculum train simultaneously as one vmapped XLA
program (the population axis is embarrassingly parallel — zero
collectives), so ONE window trains every candidate and held-out
deterministic evaluation (evaluate.py's sweep mode ranks all members)
selects the winner.

Composition of two existing shells, not new machinery:

- the functional iteration is ``curriculum.make_hetero_iteration`` —
  the exact program ``HeteroTrainer`` jits — ``jax.vmap``-ed over a
  leading (K,) member axis (the ``SweepTrainer`` pattern,
  train/sweep.py);
- member ``i`` follows ``HeteroTrainer(seed=config.seed + i)``'s key
  discipline exactly — init split, per-stage count/env splits — so a
  population member IS the corresponding single run (equivalence pinned
  at float tolerance by tests/test_hetero_sweep.py; over hundreds of
  iterations the vmapped and single programs can drift apart through
  fusion-level rounding on this chaotic objective, as any two
  compilations of the same run can);
- artifacts follow the sweep contract: per-member checkpoints under
  ``{log_dir}/seed{i}/`` (standard single-run tooling plays them back)
  plus ``sweep_summary.json``, so ``evaluate.py name=run`` ranks all
  members and ``visualize_policy.py`` descends to the best member with
  no new code.

Deliberate scope (documented restrictions, enforced loudly):
single-controller only (the config-5 acceptance runs on one chip; use
``SweepTrainer`` for multi-host populations), no per-member learning
rates, and no ``iters_per_dispatch`` (retired for sweeps).
``fused_chunk=K`` (round 6) DOES compose: within a stage, K vmapped
iterations fuse into one ``lax.scan`` dispatch — chunks clip at the
host-driven stage boundaries (a stage tail shorter than K compiles its
own scan length, once, cached), telemetry drains double-buffered, and
population checkpoints write async off a device-side snapshot at chunk
boundaries (``tests/test_fused_sweep.py`` pins bitwise parity with the
host loop across stage changes). ``resume=true`` restores the latest
``sweep_state_*`` population checkpoint — params, batched optimizer
state, member PRNG streams, env state, per-member counters, and the
curriculum cursor — and continues bit-identically to an uninterrupted
run, including MID-stage (the partially-walked stage is not resampled).
Operationally critical on the short-window tunneled chip, where the
K-candidate curriculum is the longest stage in the validation queue.
An optional ``mesh={dp: D}`` shards the member axis over devices
(``jax_compat.shard_map``, K % D == 0), which is the 7th ``dryrun_multichip``
path (__graft_entry__.py).
"""

from __future__ import annotations

import functools
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax.training.train_state import TrainState

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.hetero import (
    hetero_compute_obs,
    hetero_reset_batch,
)
from marl_distributedformation_tpu.jax_compat import shard_map
from marl_distributedformation_tpu.models import MLPActorCritic
from marl_distributedformation_tpu.train.curriculum import (
    Curriculum,
    CurriculumStage,
    make_hetero_iteration,
    sample_stage_counts,
)
from marl_distributedformation_tpu.train.recovery import record_health_flags
from marl_distributedformation_tpu.train.sweep import (
    population_aggregate,
    write_sweep_summary,
)
from marl_distributedformation_tpu.train.trainer import (
    TrainConfig,
    fill_ent_schedule,
    make_fused_chunk,
)
from marl_distributedformation_tpu.utils import (
    AsyncCheckpointWriter,
    MetricsLogger,
    Throughput,
    device_snapshot,
    latest_sweep_state,
    own_restored,
    repo_root,
    save_sweep_state,
)
from marl_distributedformation_tpu.utils import profiling
from marl_distributedformation_tpu.utils.checkpoint import (
    _write_atomic,
    checkpoint_path,
)

Array = jax.Array


class HeteroSweepTrainer:
    """K candidate seeds of the hetero curriculum under one jit.

    Args:
      curriculum / env_params / ppo / config: as :class:`HeteroTrainer`.
      num_seeds: population size K; member ``i`` trains at seed
        ``config.seed + i``.
      model: policy module shared across members (fresh params per
        member); agent-factored MLP or per-formation CTDE.
      mesh: optional ``jax.sharding.Mesh`` whose ``'dp'`` axis shards the
        member axis (K must divide by it).
    """

    def __init__(
        self,
        curriculum: Curriculum = Curriculum(),
        env_params: Optional[EnvParams] = None,
        ppo: PPOConfig = PPOConfig(),
        config: TrainConfig = TrainConfig(),
        num_seeds: int = 4,
        model: Any = None,
        mesh: Any = None,
    ) -> None:
        assert num_seeds >= 1
        if jax.process_count() > 1:
            raise SystemExit(
                "HeteroSweepTrainer is single-controller: the config-5 "
                "candidate workflow runs on one chip. Multi-host "
                "populations are SweepTrainer's domain (drop the "
                "curriculum), or run one process."
            )
        if int(config.iters_per_dispatch) > 1:
            raise SystemExit(
                "iters_per_dispatch is retired for population sweeps — "
                "set fused_chunk=K instead (chunks clip at curriculum "
                "stage boundaries, so staged training now composes with "
                "scan fusion)"
            )
        self._fused_chunk = max(0, int(config.fused_chunk))
        self.curriculum = curriculum
        if env_params is None:
            env_params = EnvParams()
        self.env_params = env_params.replace(
            num_agents=max(curriculum.max_agents, env_params.num_agents),
            num_obstacles=max(
                curriculum.max_obstacles, env_params.num_obstacles
            ),
        )
        ppo = fill_ent_schedule(
            ppo, self.env_params, config,
            iterations=curriculum.total_rollouts,
        )
        self.ppo = ppo
        self.config = config
        self.num_seeds = num_seeds
        self.model = model or MLPActorCritic(
            act_dim=self.env_params.act_dim, log_std_init=ppo.log_std_init
        )
        self.per_formation = getattr(self.model, "per_formation", False)

        if self.per_formation:
            dummy_obs = jnp.zeros(
                (1, self.env_params.num_agents, self.env_params.obs_dim),
                jnp.float32,
            )
        else:
            dummy_obs = jnp.zeros(
                (1, self.env_params.obs_dim), jnp.float32
            )
        model_ref = self.model
        tx = ppo.make_optimizer()

        def init_member(seed: Array):
            # EXACTLY HeteroTrainer.__init__'s key discipline so member i
            # == HeteroTrainer(seed=config.seed + i) (same PRNG streams;
            # equivalence pinned by tests/test_hetero_sweep.py).
            key = jax.random.PRNGKey(seed)
            key, k_init = jax.random.split(key)
            params = model_ref.init(k_init, dummy_obs)
            ts = TrainState.create(
                apply_fn=model_ref.apply, params=params, tx=tx
            )
            return ts, key

        self._mesh = mesh
        if mesh is not None:
            assert set(mesh.axis_names) == {"dp"}, (
                f"hetero-sweep meshes shard the MEMBER axis over 'dp' "
                f"only; got axes {tuple(mesh.axis_names)} (the padded "
                "dynamic ring cannot shard the agent axis — see "
                "HeteroTrainer)"
            )
            dp = int(mesh.shape["dp"])
            assert num_seeds % dp == 0, (
                f"num_seeds={num_seeds} must be divisible by the mesh dp "
                f"axis ({dp})"
            )

        seeds = config.seed + jnp.arange(num_seeds)
        self.train_state, self.key = jax.jit(jax.vmap(init_member))(seeds)

        iteration = make_hetero_iteration(
            self.env_params, ppo, self.per_formation
        )
        # In-program health word + skip-update guard (train/recovery.py),
        # wrapped before the vmap so each curriculum candidate carries
        # and acts on its own flags.
        from marl_distributedformation_tpu.train.recovery import wrap_health

        iteration = wrap_health(iteration, config)
        iteration_pop = jax.vmap(iteration)
        if mesh is not None:
            # shard_map over the member axis (not bare jit-under-mesh):
            # members are independent, each device runs K/D of them
            # entirely locally — provably zero collectives (the
            # SweepTrainer rationale, train/sweep.py).
            from jax.sharding import PartitionSpec

            spec = PartitionSpec("dp")
            iteration_pop = shard_map(
                iteration_pop,
                mesh=mesh,
                in_specs=spec,
                out_specs=spec,
                check_vma=False,
            )
        self._iteration_pop = iteration_pop
        # ONE guard across the host-loop program and every fused chunk
        # length: `count` is the total number of compiles this trainer
        # triggered. A curriculum whose stage lengths divide fused_chunk
        # compiles exactly once; a clipped stage tail costs one extra
        # compile per DISTINCT tail length (cached below, never per
        # dispatch) — size the guard_retraces budget accordingly.
        self.retrace_guard = profiling.RetraceGuard(
            "hetero_sweep_iteration",
            max_traces=config.guard_retraces or None,
        )
        self._iteration = profiling.ledgered_jit(
            iteration_pop,
            self.retrace_guard,
            subsystem="hetero_sweep",
            program="hetero_sweep_iteration",
            donate_argnums=(0, 1),
        )
        self._fused_programs: Dict[int, Any] = {}

        self.env_state = None
        self.obs = None
        # Per-member active agent-transition counters (the SB3
        # num_timesteps analog; members sample their own mixes, so the
        # counts differ per member).
        self.num_timesteps_members = np.zeros(num_seeds, np.int64)
        self.completed_rollouts = 0
        self._vec_steps_since_save = 0
        self._active_agents = np.zeros(num_seeds, np.int64)
        self.log_dir = config.log_dir or str(
            repo_root() / "logs" / config.name
        )
        if config.resume:
            # Restore BEFORE mesh placement (start_stage re-places) —
            # exactly the SweepTrainer ordering. An interrupted candidate
            # block continues bit-identically instead of retraining from
            # scratch: operationally critical on the short-window
            # tunneled chip, where the K-candidate curriculum is the
            # longest single stage in the validation queue.
            self._try_resume()

    # ------------------------------------------------------------------

    @property
    def num_timesteps(self) -> int:
        """Max over members — the checkpoint-naming / budget scalar (all
        members advance the same rollout count; only their live agent
        mixes differ)."""
        return int(self.num_timesteps_members.max(initial=0))

    @property
    def total_timesteps(self) -> int:
        """Per-member budget. NB when an explicit
        ``config.total_timesteps`` BINDS before the curriculum finishes,
        the whole population stops in LOCKSTEP once the FASTEST-counting
        member (members sample their own mixes, so active-transition
        counts differ) reaches it — slower members then see fewer
        rollouts than their standalone single run would under the same
        cap. The member == HeteroTrainer(seed+i) equivalence therefore
        holds only for non-binding caps (the candidate workflow's case:
        the cap is an upper bound, never attained with mixed stages)."""
        if self.config.total_timesteps is not None:
            return self.config.total_timesteps
        return (
            self.curriculum.total_rollouts
            * self.ppo.n_steps
            * self.config.num_formations
            * self.env_params.num_agents
        )

    def _member_stage_fn(self, stage: CurriculumStage):
        """Per-member stage reset ``key -> (key, env_state, obs)`` — the
        ONE definition of the stage key-split/reset/obs discipline, used
        live by ``start_stage`` and shape-only (``jax.eval_shape``) by
        ``_state_template`` so the resume template cannot drift from the
        real state structure."""
        m = self.config.num_formations
        env_params = self.env_params

        def member_stage(key: Array):
            key, k_counts, k_env = jax.random.split(key, 3)
            n_agents, n_obstacles = sample_stage_counts(k_counts, stage, m)
            env_state = hetero_reset_batch(
                k_env, env_params, n_agents, n_obstacles
            )
            obs = jax.vmap(hetero_compute_obs, in_axes=(0, None))(
                env_state, env_params
            )
            return key, env_state, obs

        return member_stage

    def start_stage(self, stage: CurriculumStage) -> None:
        """Resample every member's formation mix and reset its envs —
        the vmapped analog of ``HeteroTrainer.start_stage`` (each member
        draws its OWN mix from its own key stream, preserving the
        member == single-run equivalence)."""
        self.key, self.env_state, self.obs = jax.jit(
            jax.vmap(self._member_stage_fn(stage))
        )(self.key)
        self._place_on_mesh()
        self._refresh_active_agents()

    def _place_on_mesh(self) -> None:
        """(Re-)place the whole population on the dp mesh — after a stage
        reset or a resume restore; no-op unmeshed."""
        if self._mesh is None:
            return
        from jax.sharding import NamedSharding, PartitionSpec

        shard = NamedSharding(self._mesh, PartitionSpec("dp"))
        place = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: jax.device_put(x, shard), t
        )
        self.train_state = place(self.train_state)
        self.env_state = place(self.env_state)
        self.obs = place(self.obs)
        self.key = place(self.key)

    def _refresh_active_agents(self) -> None:
        # ONE host pull for the per-member active-agent counts.
        self._active_agents = np.asarray(
            jax.device_get(self.env_state.n_agents.sum(axis=-1)), np.int64
        )

    def run_iteration(self) -> Dict[str, Array]:
        """One vectorized iteration; metric values carry a leading (K,)
        member axis."""
        assert self.env_state is not None, "call start_stage() first"
        (
            self.train_state,
            self.env_state,
            self.obs,
            self.key,
            metrics,
        ) = self._iteration(
            self.train_state, self.env_state, self.obs, self.key
        )
        self.num_timesteps_members += self.ppo.n_steps * self._active_agents
        self.completed_rollouts += 1
        self._vec_steps_since_save += self.ppo.n_steps
        return metrics

    def _fused_dispatch(self, r: int):
        """The jitted fused program for an ``r``-iteration chunk, cached
        per length. Stage boundaries are host-driven env rebuilds, so a
        chunk never crosses one — stage tails shorter than ``fused_chunk``
        dispatch through a shorter scan, compiled once per distinct
        length and shared by every stage with that remainder."""
        fn = self._fused_programs.get(r)
        if fn is None:
            # One ledger entry per DISTINCT chunk length — exactly the
            # compile cadence the shared guard already accounts for.
            fn = profiling.ledgered_jit(
                make_fused_chunk(self._iteration_pop, r),
                self.retrace_guard,
                subsystem="hetero_sweep",
                program=f"hetero_sweep_chunk_k{r}",
                donate_argnums=(0, 1),
            )
            self._fused_programs[r] = fn
        return fn

    def run_chunk(self, r: Optional[int] = None) -> Dict[str, Array]:
        """Anakin mode: dispatch ONE fused-scan chunk of ``r`` vmapped
        iterations (default ``fused_chunk``; callers clip ``r`` at stage
        boundaries) and return the stacked ``(r, num_seeds, ...)`` device
        metrics. Returns as soon as the program is enqueued."""
        assert self._fused_chunk > 0, (
            "run_chunk() needs fused_chunk > 0 (Anakin mode)"
        )
        assert self.env_state is not None, "call start_stage() first"
        r = self._fused_chunk if r is None else int(r)
        (
            self.train_state,
            self.env_state,
            self.obs,
            self.key,
            stacked,
        ) = self._fused_dispatch(r)(
            self.train_state, self.env_state, self.obs, self.key
        )
        # Active-agent mixes are frozen within a stage and chunks never
        # cross one, so the per-member accounting of r host iterations
        # collapses to one increment.
        self.num_timesteps_members += r * self.ppo.n_steps * self._active_agents
        self.completed_rollouts += r
        self._vec_steps_since_save += r * self.ppo.n_steps
        return stacked

    def train(self) -> Dict[str, float]:
        """Run the full curriculum for every member; logs population
        aggregates per rollout (sweep metric contract: ``reward`` is the
        population mean plus ``reward_best``/``reward_worst``/
        ``best_seed``) and writes per-member checkpoints + the ranking
        summary at the end."""
        if self._fused_chunk:
            return self._train_fused()
        logger = MetricsLogger(
            self.log_dir,
            run_name=self.config.name,
            use_wandb=self.config.use_wandb,
            use_tensorboard=self.config.use_tensorboard,
        )
        meter = Throughput()
        tracer = profiling.TraceWindow(
            self.log_dir, self.config.profile, self.config.profile_iterations
        )
        record: Dict[str, float] = {}
        # Resume continuity: the log_interval cadence is phased on the
        # GLOBAL rollout index, so a resumed run logs the same rollouts
        # an uninterrupted one would.
        iteration = self.completed_rollouts
        metrics = None
        done_budget = False
        try:
            stage_end = 0
            for stage_idx, stage in enumerate(self.curriculum.stages):
                if done_budget:
                    break
                stage_start = stage_end
                stage_end = stage_start + stage.rollouts
                if self.completed_rollouts >= stage_end:
                    continue  # resumed past this stage — don't replay it
                if (
                    self.config.total_timesteps is not None
                    and self.num_timesteps >= self.config.total_timesteps
                ):
                    # Budget bound BEFORE the stage reset: starting the
                    # stage just to stop would burn a key split and an env
                    # resample, so the final checkpoint would hold
                    # post-reset state — and a resume (completed_rollouts
                    # == stage_start) would re-run start_stage from that
                    # key and silently diverge from an uninterrupted run.
                    break
                if (
                    self.completed_rollouts == stage_start
                    or self.env_state is None
                ):
                    self.start_stage(stage)
                # else: resumed MID-stage — env/counters restored by
                # _try_resume; re-running start_stage would resample the
                # stage and break bit-exact continuation.
                for _ in range(stage_end - self.completed_rollouts):
                    if (
                        self.config.total_timesteps is not None
                        and self.num_timesteps
                        >= self.config.total_timesteps
                    ):
                        done_budget = True
                        break
                    tracer.before_dispatch()
                    metrics = self.run_iteration()
                    tracer.after_dispatch(metrics)
                    iteration += 1
                    meter.tick(
                        self.ppo.n_steps
                        * self.config.num_formations
                        * self.num_seeds
                    )
                    if iteration % self.config.log_interval == 0:
                        host = jax.device_get(metrics)  # one batched pull
                        record_health_flags(host)  # drain-seam counter
                        record = self._aggregate(host)
                        record["env_steps_per_sec"] = meter.rate()
                        record["curriculum_stage"] = float(stage_idx)
                        logger.log(record, self.num_timesteps)
                    if (
                        self.config.checkpoint
                        and self._vec_steps_since_save
                        >= self.config.save_freq
                    ):
                        self.save()
            if metrics is not None and self.config.checkpoint:
                # Rank on the final iteration's rewards, matching the
                # final checkpoints (the SweepTrainer rule).
                final = jax.device_get(metrics)
                self.save()
                self._write_summary(np.asarray(final["reward"]))
        finally:
            tracer.close()
            logger.close()
        return record

    # ------------------------------------------------------------------
    # Anakin mode (fused_chunk > 0): fused-scan chunks clipped at stage
    # boundaries, double-buffered drain, async population checkpoints.
    # ------------------------------------------------------------------

    def _train_fused(self) -> Dict[str, float]:
        """Fused-scan curriculum driver. The stage walk is the host
        loop's — stage resets stay host-driven — but within a stage the
        iterations dispatch as fused chunks of ``min(fused_chunk,
        rollouts left in the stage)``: chunk N+1 (or the next stage's
        first chunk) is dispatched BEFORE chunk N's stacked telemetry
        drains, and population checkpoints write on the background
        writer off a device-side snapshot at chunk boundaries. An
        explicit ``total_timesteps`` cap quantizes to the chunk (checked
        between dispatches — the member == single-run equivalence
        already only holds for non-binding caps, see
        ``total_timesteps``)."""
        logger = MetricsLogger(
            self.log_dir,
            run_name=self.config.name,
            use_wandb=self.config.use_wandb,
            use_tensorboard=self.config.use_tensorboard,
        )
        meter = Throughput()
        writer = AsyncCheckpointWriter() if self.config.checkpoint else None
        tracer = profiling.TraceWindow(
            self.log_dir, self.config.profile, self.config.profile_iterations
        )
        record: Dict[str, float] = {}
        final_rewards = None
        pending = None  # the chunk in flight, drained one dispatch later
        done_budget = False
        try:
            stage_end = 0
            for stage_idx, stage in enumerate(self.curriculum.stages):
                if done_budget:
                    break
                stage_start = stage_end
                stage_end = stage_start + stage.rollouts
                if self.completed_rollouts >= stage_end:
                    continue  # resumed past this stage — don't replay it
                if (
                    self.config.total_timesteps is not None
                    and self.num_timesteps >= self.config.total_timesteps
                ):
                    # Budget bound before the stage reset (the host-loop
                    # rule): never burn a key split on a stage that will
                    # not train — the boundary checkpoint must hold the
                    # PRE-reset key so resume replays start_stage exactly
                    # once, identically to an uninterrupted run.
                    break
                if (
                    self.completed_rollouts == stage_start
                    or self.env_state is None
                ):
                    self.start_stage(stage)
                # else: resumed MID-stage — continue without resampling
                # (the host-loop rule); the next chunks re-clip to the
                # stage remainder, so resume re-enters bit-exactly.
                while self.completed_rollouts < stage_end:
                    if (
                        self.config.total_timesteps is not None
                        and self.num_timesteps
                        >= self.config.total_timesteps
                    ):
                        done_budget = True
                        break
                    r = min(
                        self._fused_chunk,
                        stage_end - self.completed_rollouts,
                    )
                    first_iteration = self.completed_rollouts
                    steps_before = self.num_timesteps_members.copy()
                    active = self._active_agents.copy()
                    tracer.before_dispatch()
                    stacked = self.run_chunk(r)
                    tracer.after_dispatch(stacked)
                    if pending is not None:
                        rec, final_rewards = self._drain_chunk(
                            logger, meter, *pending
                        )
                        record = rec or record
                    pending = (
                        stacked, r, first_iteration, steps_before,
                        active, stage_idx,
                    )
                    if (
                        writer is not None
                        and self._vec_steps_since_save
                        >= self.config.save_freq
                    ):
                        self.save_async(writer)
            if pending is not None:
                rec, final_rewards = self._drain_chunk(
                    logger, meter, *pending
                )
                record = rec or record
            if self.config.checkpoint:
                if writer is not None:
                    self.save_async(writer)
                    writer.close()  # final write durable before the summary
                    writer = None
                if final_rewards is not None:
                    self._write_summary(final_rewards)
        finally:
            tracer.close()
            if writer is not None:
                writer.close_quietly()
            logger.close()
        return record

    def _drain_chunk(self, logger, meter, stacked, r, first_iteration,
                     steps_before, active, stage_idx):
        """ONE batched ``device_get`` for a chunk's population telemetry;
        emit per-iteration aggregate records at the host loop's step
        stamps (reconstructed from the per-member counters BEFORE the
        chunk plus the stage's frozen active-agent counts). Returns
        ``(last_emitted_record, final_iteration_rewards)``."""
        host = jax.device_get(stacked)
        profiling.sample_device_watermark()  # drain boundary (ledger)
        # Drain-seam health pin (train/recovery.py): per-member skips
        # land in train_skipped_updates_total with the same batched
        # device_get the telemetry already paid for.
        record_health_flags(host)
        meter.tick(
            r * self.ppo.n_steps * self.config.num_formations
            * self.num_seeds
        )
        record: Dict[str, float] = {}
        for i in range(r):
            if (first_iteration + i + 1) % self.config.log_interval:
                continue
            rec = self._aggregate(
                {name: v[i] for name, v in host.items()}
            )
            rec["env_steps_per_sec"] = meter.rate()
            rec["curriculum_stage"] = float(stage_idx)
            step = int(
                (steps_before + (i + 1) * self.ppo.n_steps * active).max()
            )
            logger.log(rec, step)
            record = rec
        return record, np.asarray(host["reward"][-1])

    def _aggregate(self, host: Dict[str, np.ndarray]) -> Dict[str, float]:
        return population_aggregate(host, self.config.seed)

    def _device_target(self) -> Dict[str, Any]:
        return {
            "params": self.train_state.params,
            "opt_state": self.train_state.opt_state,
            "key": self.key,
            "env_state": self.env_state,
            "obs": self.obs,
        }

    def _write_population_files(
        self, tree: Dict[str, Any], members: np.ndarray, rollouts: int
    ) -> None:
        """Write one LOGICAL population checkpoint: per-member
        ``seed{i}/rl_model_*`` files (standard single-run tooling plays
        them back / fine-tunes them) plus the ``sweep_state`` resume
        anchor. ``tree`` is a host pull or a ``device_snapshot`` (the
        async writer thread drains either in one batched ``device_get``);
        ``members``/``rollouts`` are the progress counters captured when
        the checkpoint was requested. The anchor writes LAST so discovery
        never finds an anchor whose member files are missing."""
        host = jax.device_get(tree)
        for i in range(self.num_seeds):
            # np.array: owning copies, not views keeping the full
            # population tree alive (the SweepTrainer.member_state rule).
            take = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda x: np.array(x[i]), t
            )
            state = {
                "policy": self.model.__class__.__name__,
                "params": take(host["params"]),
                "opt_state": take(host["opt_state"]),
                "key": np.array(host["key"][i]),
                "num_timesteps": int(members[i]),
                "completed_rollouts": int(rollouts),
            }
            _write_atomic(
                checkpoint_path(
                    Path(self.log_dir) / f"seed{i}", int(members[i])
                ),
                state,
            )
        # ONE population-state file so an interrupted block RESUMES
        # (resume=true) mid-curriculum instead of retraining from
        # scratch — the identity fields are validated on restore.
        save_sweep_state(
            self.log_dir,
            int(members.max(initial=0)),
            {
                "policy": self.model.__class__.__name__,
                "num_seeds": self.num_seeds,
                "seed": int(self.config.seed),
                "num_formations": int(self.config.num_formations),
                "curriculum_spec": self._curriculum_spec(),
                "num_timesteps_members": np.asarray(members),
                "completed_rollouts": int(rollouts),
                **{
                    k: host[k]
                    for k in ("params", "opt_state", "key",
                              "env_state", "obs")
                },
            },
        )

    def save(self) -> None:
        """Synchronous population checkpoint: one batched device pull
        serves every member (tunneled-TPU rule: sync once, slice on
        host), then per-member files + the sweep_state anchor."""
        self._write_population_files(
            jax.device_get(self._device_target()),
            self.num_timesteps_members.copy(),
            self.completed_rollouts,
        )
        self._vec_steps_since_save = 0

    def save_async(self, writer: AsyncCheckpointWriter) -> None:
        """Chunk-boundary population checkpoint off a device-side
        snapshot (``utils.device_snapshot``): the writer thread drains
        and writes while the device runs the next chunk; the progress
        counters are captured NOW, so the files record the state the
        snapshot actually holds."""
        writer.submit_write(
            functools.partial(
                self._write_population_files,
                device_snapshot(self._device_target()),
                self.num_timesteps_members.copy(),
                self.completed_rollouts,
            )
        )
        self._vec_steps_since_save = 0

    def _state_template(self):
        """Host-side zero template with the population shapes — env/obs
        shapes come from ``jax.eval_shape`` over the SAME stage-reset
        function ``start_stage`` runs (no PRNG is consumed, no device
        compute runs)."""
        _, env_shape, obs_shape = jax.eval_shape(
            jax.vmap(self._member_stage_fn(self.curriculum.stages[0])),
            self.key,
        )
        zeros = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: np.zeros(x.shape, x.dtype), t
        )
        return {
            "params": zeros(self.train_state.params),
            "opt_state": zeros(self.train_state.opt_state),
            "key": zeros(self.key),
            "env_state": zeros(env_shape),
            "obs": zeros(obs_shape),
        }

    def _curriculum_spec(self) -> str:
        """Canonical string of the full stage structure for the resume
        identity check (msgpack-friendly; compared verbatim)."""
        return repr(
            [
                (s.rollouts, tuple(s.agent_counts),
                 None if s.probs is None else tuple(s.probs),
                 s.num_obstacles)
                for s in self.curriculum.stages
            ]
        )

    def _try_resume(self) -> None:
        """Restore the latest ``sweep_state_*`` population checkpoint:
        params, batched optimizer state, member PRNG streams, env state,
        per-member transition counters, and the curriculum cursor — the
        resumed run continues bit-identically to an uninterrupted one
        (pinned by tests/test_hetero_sweep.py)."""
        from flax import serialization

        path = latest_sweep_state(self.log_dir)
        if path is None:
            print(
                "[hetero-sweep] resume=true but no sweep_state_* "
                f"population checkpoint under {self.log_dir}; starting "
                "fresh"
            )
            return
        from marl_distributedformation_tpu.utils.checkpoint import (
            msgpack_restore_file,
        )

        raw = msgpack_restore_file(path)
        ident = {
            "policy": self.model.__class__.__name__,
            "num_seeds": self.num_seeds,
            "seed": int(self.config.seed),
            "num_formations": int(self.config.num_formations),
            # The FULL stage structure, not just the rollout total — a
            # reshuffled curriculum with the same total would otherwise
            # resume onto wrong stage boundaries.
            "curriculum_spec": self._curriculum_spec(),
        }
        for field, want in ident.items():
            got = raw.get(field)
            if got != want and str(got) != str(want):
                raise SystemExit(
                    f"hetero-sweep resume mismatch: {path} was written "
                    f"with {field}={got!r} but this run uses {want!r} — "
                    "candidate identities would silently change"
                )
        template = self._state_template()
        for name in (*template, "num_timesteps_members",
                     "completed_rollouts"):
            if name not in raw:
                raise SystemExit(
                    f"hetero-sweep resume: {path} is missing {name!r} — "
                    "truncated or foreign file"
                )
        restored = {
            name: serialization.from_state_dict(tmpl, raw[name])
            for name, tmpl in template.items()
        }
        # Owning copies BEFORE the donating dispatch sees this state
        # (utils.own_restored: msgpack leaves can alias the checkpoint
        # bytes; donation of an aliased buffer is a use-after-free on
        # the zero-copy CPU backend).
        restored = own_restored(restored)
        self.train_state = self.train_state.replace(
            params=restored["params"], opt_state=restored["opt_state"]
        )
        self.key = jnp.asarray(restored["key"])
        self.env_state = restored["env_state"]
        self.obs = jnp.asarray(restored["obs"])
        # np.array (owning copy): msgpack_restore hands back read-only
        # buffers, and this counter is incremented in place per rollout.
        self.num_timesteps_members = np.array(
            raw["num_timesteps_members"], np.int64
        )
        self.completed_rollouts = int(raw["completed_rollouts"])
        self._place_on_mesh()
        self._refresh_active_agents()
        # Drop metrics rows the resumed run will re-log (the logger
        # appends; rollouts past the restored checkpoint were recorded
        # by the interrupted attempt and are about to replay) — the
        # banked curve must carry each rollout once.
        mpath = Path(self.log_dir) / "metrics.jsonl"
        if mpath.exists():
            import json

            kept = [
                ln
                for ln in mpath.read_text().splitlines()
                if ln.strip()
                and json.loads(ln).get("step", 0) <= self.num_timesteps
            ]
            mpath.write_text("".join(ln + "\n" for ln in kept))
        print(
            f"[hetero-sweep] resumed {self.num_seeds}-candidate block "
            f"from {path} at rollout {self.completed_rollouts}/"
            f"{self.curriculum.total_rollouts}"
        )

    def _write_summary(self, rewards: np.ndarray) -> None:
        write_sweep_summary(
            self.log_dir,
            self.config.seed,
            self.num_seeds,
            rewards,
            {"curriculum_rollouts": self.curriculum.total_rollouts},
        )
