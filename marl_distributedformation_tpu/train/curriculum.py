"""Curriculum over formation size + obstacle count, and the hetero trainer.

BASELINE.json config 5: "Heterogeneous multi-formation (mixed 5/20-agent
groups) with obstacle field, curriculum over num_agents_per_formation". The
reference has no curriculum machinery — every run fixes one
``num_agents_per_formation`` for all formations forever
(reference ``vectorized_env.py:39-43``, ``cfg/config.yaml:4``).

TPU-first design: the padded heterogeneous env (env/hetero.py) keeps all
shapes static at ``(M, N_max, ...)`` while the *active* counts are data, so a
stage transition is just resampling two ``(M,)`` int32 arrays and resetting —
the jitted training iteration is compiled exactly once for the whole
curriculum. Contrast the reference, where changing ``num_agents_per_formation``
means rebuilding every simulator object and the SB3 model.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax.training.train_state import TrainState

from marl_distributedformation_tpu.algo import (
    MinibatchData,
    PPOConfig,
    collect_rollout,
    compute_gae,
    ppo_update,
)
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.hetero import (
    HeteroState,
    agent_mask,
    hetero_compute_obs,
    hetero_reset_batch,
    hetero_step_batch,
)
from marl_distributedformation_tpu.models import MLPActorCritic
from marl_distributedformation_tpu.train.trainer import (
    TrainConfig,
    fill_ent_schedule,
)
from marl_distributedformation_tpu.utils import (
    MetricsLogger,
    Throughput,
    latest_checkpoint,
    repo_root,
    restore_checkpoint,
    save_checkpoint,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class CurriculumStage:
    """One curriculum phase.

    ``agent_counts``/``probs`` define the per-formation size distribution —
    each formation slot independently draws its agent count for the whole
    stage. ``num_obstacles`` is the active obstacle count per formation
    (the obstacle *capacity* ``EnvParams.num_obstacles`` stays static).
    """

    rollouts: int
    agent_counts: Tuple[int, ...]
    probs: Optional[Tuple[float, ...]] = None
    num_obstacles: int = 0

    def __post_init__(self) -> None:
        assert self.rollouts > 0
        assert len(self.agent_counts) >= 1
        assert all(n >= 2 for n in self.agent_counts)
        if self.probs is not None:
            assert len(self.probs) == len(self.agent_counts)


@dataclasses.dataclass(frozen=True)
class Curriculum:
    """An ordered sequence of stages.

    The default mirrors the BASELINE.json config-5 storyline: learn plain
    5-agent formations, mix in 20-agent groups, then add an obstacle field.
    """

    stages: Tuple[CurriculumStage, ...] = (
        CurriculumStage(rollouts=40, agent_counts=(5,)),
        CurriculumStage(rollouts=40, agent_counts=(5, 20)),
        CurriculumStage(rollouts=20, agent_counts=(5, 20), num_obstacles=4),
    )

    @property
    def max_agents(self) -> int:
        return max(max(s.agent_counts) for s in self.stages)

    @property
    def max_obstacles(self) -> int:
        return max(s.num_obstacles for s in self.stages)

    @property
    def total_rollouts(self) -> int:
        return sum(s.rollouts for s in self.stages)


def sample_stage_counts(
    key: Array, stage: CurriculumStage, num_formations: int
) -> Tuple[Array, Array]:
    """Draw per-formation ``(n_agents, n_obstacles)`` for a stage."""
    counts = jnp.asarray(stage.agent_counts, jnp.int32)
    if stage.probs is None:
        idx = jax.random.randint(key, (num_formations,), 0, counts.shape[0])
    else:
        idx = jax.random.choice(
            key,
            counts.shape[0],
            (num_formations,),
            p=jnp.asarray(stage.probs, jnp.float32),
        )
    n_agents = counts[idx]
    n_obstacles = jnp.full((num_formations,), stage.num_obstacles, jnp.int32)
    return n_agents, n_obstacles


class HeteroTrainer:
    """PPO over padded heterogeneous formations with a stage curriculum.

    Same imperative-shell shape as ``train.Trainer`` (rollout + GAE + all
    minibatch epochs in ONE jitted program per iteration); differences:

    - env state is ``HeteroState`` with per-formation dynamic counts;
    - padded agents carry zero loss weight (``MinibatchData.weights``);
    - ``train()`` walks the curriculum, resampling counts and resetting the
      env at each stage boundary — no recompilation across stages;
    - timestep accounting counts *active* agent-transitions (the SB3
      ``num_timesteps`` analogue, SURVEY.md §2.2, scaled to the live mix).

    ``model`` may be agent-factored (the shared per-agent MLP — the
    reference's parameter-sharing trick, ``vectorized_env.py:32``) or
    per-formation (``CTDEActorCritic``): per-formation models receive the
    ``(M, N_max)`` agent-validity mask in every forward pass — rollout and
    update — so padded agents are excluded from the pooled critic, their
    values are 0, and their transitions carry zero loss weight.
    """

    def __init__(
        self,
        curriculum: Curriculum = Curriculum(),
        env_params: Optional[EnvParams] = None,
        ppo: PPOConfig = PPOConfig(),
        config: TrainConfig = TrainConfig(),
        model: Any = None,
        shard_fn: Any = None,
    ) -> None:
        self.curriculum = curriculum
        if env_params is None:
            env_params = EnvParams()
        self.env_params = env_params.replace(
            num_agents=max(curriculum.max_agents, env_params.num_agents),
            num_obstacles=max(
                curriculum.max_obstacles, env_params.num_obstacles
            ),
        )
        # The curriculum's budget is its stage plan: the entropy-decay
        # horizon is the total rollout count across stages.
        ppo = fill_ent_schedule(
            ppo, self.env_params, config,
            iterations=curriculum.total_rollouts,
        )
        self.ppo = ppo
        self.config = config
        if int(config.iters_per_dispatch) > 1 or int(config.fused_chunk) > 0:
            # Stage boundaries are host-driven (count resampling + env
            # reset between stages); fusing iterations across them would
            # silently blur the curriculum, and fusing within a stage
            # would need stage-length-aware burst sizing. Reject loudly
            # instead of silently running at cadence 1. fused_chunk
            # (Anakin mode) fuses even harder and fails for the same
            # reason — unlike scenario schedules, curriculum stage data
            # is not a traced input to one compiled program.
            raise SystemExit(
                "iters_per_dispatch > 1 / fused_chunk do not compose with "
                "curriculum training (stage boundaries are host-driven); "
                "unset them or drop the curriculum"
            )

        self.model = model or MLPActorCritic(
            act_dim=self.env_params.act_dim, log_std_init=ppo.log_std_init
        )
        self.per_formation = getattr(self.model, "per_formation", False)
        key = jax.random.PRNGKey(config.seed)
        self.key, k_init = jax.random.split(key)
        if self.per_formation:
            dummy_obs = jnp.zeros(
                (1, self.env_params.num_agents, self.env_params.obs_dim),
                jnp.float32,
            )
        else:
            dummy_obs = jnp.zeros((1, self.env_params.obs_dim), jnp.float32)
        params = self.model.init(k_init, dummy_obs)
        self.train_state = TrainState.create(
            apply_fn=self.model.apply,
            params=params,
            tx=ppo.make_optimizer(),
        )

        self._shard_fn = shard_fn
        mesh = getattr(shard_fn, "mesh", None)
        if mesh is not None and "sp" in mesh.shape:
            raise ValueError(
                "curriculum/hetero training does not support agent-axis "
                "('sp') sharding: padded dynamic rings gather (i±1) mod n "
                "neighbors across the whole formation, which the ring "
                "halo-exchange layout cannot serve — use a dp-only mesh "
                "(mesh={dp: N})"
            )
        self.env_state: Optional[HeteroState] = None
        self.obs: Optional[Array] = None
        self.num_timesteps = 0
        self.completed_rollouts = 0  # global rollout index (for resume)
        self._vec_steps_since_save = 0
        self._active_agents = 0  # sum of n_agents across formations (host int)
        self._iteration = jax.jit(
            self._make_iteration(), donate_argnums=(0, 1)
        )
        self.log_dir = config.log_dir or str(
            repo_root() / "logs" / config.name
        )
        if config.resume:
            self._try_resume()

    # ------------------------------------------------------------------
    # Functional core
    # ------------------------------------------------------------------

    def _make_iteration(self):
        return make_hetero_iteration(
            self.env_params, self.ppo, self.per_formation
        )


    # ------------------------------------------------------------------
    # Imperative shell
    # ------------------------------------------------------------------

    @property
    def total_timesteps(self) -> int:
        """Training budget in active agent-transitions: the explicit
        ``TrainConfig.total_timesteps`` when set (an early-stop cap on top of
        the curriculum), else an upper bound over the whole curriculum (the
        exact count depends on the sampled mix; see ``num_timesteps``)."""
        if self.config.total_timesteps is not None:
            return self.config.total_timesteps
        return (
            self.curriculum.total_rollouts
            * self.ppo.n_steps
            * self.config.num_formations
            * self.env_params.num_agents
        )

    def start_stage(self, stage: CurriculumStage) -> None:
        """Resample the formation mix and reset every formation.

        Multi-host: the stage counts derive from the replicated ``self.key``
        so every host samples the identical mix, but each host materializes
        only its own formation slice of the padded state
        (``parallel.hetero_reset_batch_sharded``) — mirroring ``Trainer``'s
        multi-host construction (no full batch on any host, no cross-process
        ``device_put``).
        """
        self.key, k_counts, k_env = jax.random.split(self.key, 3)
        n_agents, n_obstacles = sample_stage_counts(
            k_counts, stage, self.config.num_formations
        )
        if jax.process_count() > 1:
            from marl_distributedformation_tpu.parallel import (
                hetero_reset_batch_sharded,
                replicate,
            )

            assert self._shard_fn is not None and getattr(
                self._shard_fn, "mesh", None
            ), "multi-host hetero training needs a mesh (cfg.mesh)"
            mesh = self._shard_fn.mesh
            self.env_state = hetero_reset_batch_sharded(
                k_env, self.env_params, n_agents, n_obstacles, mesh
            )
            self.obs = jax.jit(
                jax.vmap(hetero_compute_obs, in_axes=(0, None)),
                static_argnums=1,
            )(self.env_state, self.env_params)
            self.train_state = replicate(self.train_state, mesh)
        else:
            self.env_state = hetero_reset_batch(
                k_env, self.env_params, n_agents, n_obstacles
            )
            self.obs = jax.vmap(hetero_compute_obs, in_axes=(0, None))(
                self.env_state, self.env_params
            )
            if self._shard_fn is not None:
                # Every stage builds a fresh env state on the host; re-place
                # it (and keep params replicated) on the mesh. This also
                # covers resume, since start_stage precedes run_iteration.
                self.train_state, self.env_state, self.obs = self._shard_fn(
                    self.train_state, self.env_state, self.obs
                )
        self._active_agents = int(n_agents.sum())

    def run_iteration(self) -> Dict[str, Array]:
        assert self.env_state is not None, "call start_stage() first"
        (
            self.train_state,
            self.env_state,
            self.obs,
            self.key,
            metrics,
        ) = self._iteration(
            self.train_state, self.env_state, self.obs, self.key
        )
        self.num_timesteps += self.ppo.n_steps * self._active_agents
        self.completed_rollouts += 1
        self._vec_steps_since_save += self.ppo.n_steps
        return metrics

    def train(self) -> Dict[str, float]:
        """Run the full curriculum; returns the last emitted metrics."""
        logger = MetricsLogger(
            self.log_dir,
            run_name=self.config.name,
            use_wandb=self.config.use_wandb,
            use_tensorboard=self.config.use_tensorboard,
        )
        meter = Throughput()
        last_record: Dict[str, float] = {}
        iteration = 0
        done_budget = False
        try:
            for stage_idx, stage in enumerate(self.curriculum.stages):
                stage_end = (
                    sum(
                        s.rollouts
                        for s in self.curriculum.stages[: stage_idx + 1]
                    )
                )
                if self.completed_rollouts >= stage_end:
                    continue  # resumed past this stage — don't replay it
                self.start_stage(stage)
                remaining = stage_end - self.completed_rollouts
                for _ in range(remaining):
                    if (
                        self.config.total_timesteps is not None
                        and self.num_timesteps >= self.config.total_timesteps
                    ):
                        done_budget = True
                        break
                    metrics = self.run_iteration()
                    iteration += 1
                    meter.tick(
                        self.ppo.n_steps * self.config.num_formations
                    )
                    if iteration % self.config.log_interval == 0:
                        # Single batched device_get — per-metric float()
                        # pays one tunnel RTT per key (see Trainer.train).
                        host_metrics = jax.device_get(metrics)
                        last_record = {
                            k: float(v) for k, v in host_metrics.items()
                        }
                        last_record["env_steps_per_sec"] = meter.rate()
                        last_record["curriculum_stage"] = float(stage_idx)
                        logger.log(last_record, self.num_timesteps)
                    if (
                        self.config.checkpoint
                        and self._vec_steps_since_save
                        >= self.config.save_freq
                    ):
                        self.save()
                if done_budget:
                    break
            if self.config.checkpoint:
                self.save()
        finally:
            logger.close()
        return last_record

    # ------------------------------------------------------------------
    # Checkpointing (same write/read contract as train.Trainer)
    # ------------------------------------------------------------------

    def _checkpoint_target(self) -> Dict[str, Any]:
        return {
            "policy": self.model.__class__.__name__,
            "params": self.train_state.params,
            "opt_state": self.train_state.opt_state,
            "key": self.key,
            "num_timesteps": self.num_timesteps,
            "completed_rollouts": self.completed_rollouts,
        }

    def save(self) -> Optional[str]:
        """Coordinator returns the written path, other hosts None (see
        utils.save_checkpoint's multi-host contract)."""
        path = save_checkpoint(
            self.log_dir, self.num_timesteps, self._checkpoint_target()
        )
        self._vec_steps_since_save = 0
        return str(path) if path is not None else None

    def _try_resume(self) -> None:
        if jax.process_count() > 1:
            # Coordinator-only disk: broadcast the learner state so every
            # host agrees on params/counters (utils.broadcast_restore). The
            # "policy" name string can't ride the broadcast and is excluded.
            from marl_distributedformation_tpu.utils import broadcast_restore

            template = {
                k: v
                for k, v in self._checkpoint_target().items()
                if k != "policy"
            }
            restored = broadcast_restore(self.log_dir, template)
            if restored is None:
                return
            restored["key"] = jnp.asarray(restored["key"])
        else:
            path = latest_checkpoint(self.log_dir)
            if path is None:
                return
            restored = restore_checkpoint(path, self._checkpoint_target())
        self.train_state = self.train_state.replace(
            params=restored["params"], opt_state=restored["opt_state"]
        )
        self.key = restored["key"]
        self.num_timesteps = int(restored["num_timesteps"])
        self.completed_rollouts = int(restored["completed_rollouts"])
        # Mesh re-placement (multi-host replication included) happens in
        # start_stage via shard_fn before any iteration runs.
        print(
            f"[hetero] resumed at {self.num_timesteps} steps "
            f"({self.completed_rollouts} rollouts)"
        )


def make_hetero_iteration(env_params, ppo, per_formation: bool):
    """Build the functional hetero training iteration (rollout + GAE +
    update over padded dynamic-count formations) as one pure function —
    the heterogeneous analog of ``trainer.make_ppo_iteration``.
    Module-level so other shells can transform it: ``HeteroTrainer`` jits
    it directly; ``HeteroSweepTrainer`` (train/hetero_sweep.py) vmaps it
    over a candidate-seed population before jitting."""
    n_max = env_params.num_agents
    if per_formation:
        # Minibatch whole formations so the centralized critic sees every
        # agent; batch_size stays denominated in agent-transitions for
        # comparable SGD noise across policies (same as train.Trainer).
        update_ppo = dataclasses.replace(
            ppo, batch_size=max(1, ppo.batch_size // n_max)
        )
        row_shape = (n_max,)
    else:
        update_ppo = ppo
        row_shape = ()

    def env_step(state: HeteroState, velocity: Array):
        return hetero_step_batch(state, velocity, env_params)

    def iteration(
        train_state: TrainState,
        env_state: HeteroState,
        obs: Array,
        key: Array,
    ):
        key, k_roll, k_update = jax.random.split(key, 3)
        # n_agents is preserved across auto-resets, so one (M, N_max)
        # mask covers every step of the rollout (and the whole stage).
        mask = jax.vmap(agent_mask, in_axes=(0, None))(
            env_state.n_agents, n_max
        ).astype(jnp.float32)
        env_state, last_obs, batch, last_value = collect_rollout(
            train_state.apply_fn,
            train_state.params,
            env_state,
            obs,
            k_roll,
            env_params,
            ppo.n_steps,
            env_step_fn=env_step,
            mask=mask if per_formation else None,
        )
        advantages, returns = compute_gae(
            batch.rewards,
            batch.values,
            batch.dones,
            last_value,
            ppo.gamma,
            ppo.gae_lambda,
        )
        weights = jnp.broadcast_to(
            mask[None], (ppo.n_steps, *mask.shape)
        ).reshape(-1, *row_shape)
        flat = MinibatchData(
            obs=batch.obs.reshape(-1, *row_shape, env_params.obs_dim),
            actions=batch.actions.reshape(
                -1, *row_shape, env_params.act_dim
            ),
            old_log_probs=batch.log_probs.reshape(-1, *row_shape),
            advantages=advantages.reshape(-1, *row_shape),
            returns=returns.reshape(-1, *row_shape),
            weights=weights,
            mask=weights if per_formation else None,
        )
        train_state, update_metrics = ppo_update(
            train_state, flat, k_update, update_ppo
        )
        metrics = {k: v.mean() for k, v in batch.metrics.items()}
        metrics.update(update_metrics)
        w_flat = weights.reshape(-1)
        w = jnp.maximum(w_flat.sum(), 1.0)
        metrics["reward"] = (batch.rewards.reshape(-1) * w_flat).sum() / w
        # Formation-level episode count: batch.dones is the per-formation
        # done broadcast to all N_max agent rows (rollout.py), so a plain
        # sum counts every padded row, inflating the count x N_max.
        # Agent row 0 is always active (n >= 2).
        metrics["episode_dones"] = batch.dones[..., 0].sum()
        return train_state, env_state, last_obs, key, metrics

    return iteration


def curriculum_from_cfg(cfg: Any) -> Curriculum:
    """Build a ``Curriculum`` from the Hydra config's ``curriculum`` list
    (cfg/config.yaml) — each entry: ``{rollouts, agent_counts, probs?,
    num_obstacles?}``. A YAML string (the form a quoted CLI override or the
    documented example produces) is parsed first."""
    if isinstance(cfg, str):
        import yaml

        cfg = yaml.safe_load(cfg)
    stages = []
    for entry in cfg:
        stages.append(
            CurriculumStage(
                rollouts=int(entry["rollouts"]),
                agent_counts=tuple(int(n) for n in entry["agent_counts"]),
                probs=(
                    tuple(float(p) for p in entry["probs"])
                    if entry.get("probs") is not None
                    else None
                ),
                num_obstacles=int(entry.get("num_obstacles", 0)),
            )
        )
    return Curriculum(stages=tuple(stages))
