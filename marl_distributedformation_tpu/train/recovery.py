"""Self-healing training lane: in-program health, host-side escalation.

The other four lanes of the always-learning loop already degrade instead
of dying — serving circuit-breaks and fails over, the pipeline watchdogs
and rolls back, the mesh survives ``kill -9``, checkpoints quarantine
their own corruption. The TRAIN lane did not: a diverged trainer (NaN
loss, exploding grad norm, an actuator-fault curriculum pushed too hard
by the adversarial feedback loop) either died on ``nan_guard`` or burned
compute writing non-finite checkpoints for the gate to reject one at a
time. Worse, fused dispatch (``fused_chunk=K``) commits K iterations per
host round trip, so by the time the host SEES a bad metric the damage is
K steps deep — detection has to ride *inside* the compiled program.

Three layers (docs/recovery.md):

1. **In-program health word** (:func:`make_health_iteration`): every
   train iteration computes four flags — finite loss, finite global grad
   norm, bounded global grad norm, bounded param-norm drift — packs them
   into a ``health_word`` metric, and applies a ``jnp.where`` **skip-
   update guard**: a flagged iteration carries the PREVIOUS state
   through unchanged (the identity update) instead of committing the
   poisoned one. The flags ride the existing stacked chunk metrics, so
   the fused drain sees them at ZERO extra dispatches, budget-1 compile
   receipts hold with health ON, and a healthy run's outputs are
   BITWISE identical health ON vs OFF (``jnp.where(True, new, old)``
   selects ``new`` exactly; tests/test_recovery.py pins it).

2. **Host-side escalation ladder** (:class:`RecoveryLadder`), consumed
   at the drain seam (never a per-iteration device probe — graftlint
   rule 22 statically rejects that anti-pattern): skipped-update
   counters -> sustained-breach ROLLBACK to the last-good checkpoint
   with a folded-in recovery counter advancing the PRNG stream (the
   retry must not bitwise-replay the divergence) and optional
   lr/severity backoff -> bounded retries, then HALT with a flight
   record. Every transition is one line in ``logs/{name}/recovery.jsonl``
   and a ``train_*`` gauge in the merged metrics namespace.

3. **Chaos closure**: the train-lane injection points
   (``train.carry_poison`` / ``train.grad_bomb`` / ``train.snapshot``,
   chaos/plane.py) plus ``scripts/chaos_storm.py --train`` drive NaN
   bombs through a live fused run and check the lane's invariants: no
   non-finite checkpoint ever becomes visible to discovery, the run
   always terminates with finite params, recovery MTTR is bounded.

This module imports jax/optax for the compiled half only; the ladder
half records through obs/ lazily so a host process can import it
without touching the device.
"""

from __future__ import annotations

import dataclasses
import json
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

RECOVERY_LOG = "recovery.jsonl"

#: Health-word bit layout (a flagged iteration has at least one bit
#: CLEAR; HEALTH_ALL means every check passed). The word rides the
#: metrics stack as a float (metrics trees are homogeneous f32), decoded
#: host-side by the ladder for recovery.jsonl detail.
HEALTH_LOSS_FINITE = 1  # loss is finite
HEALTH_GRAD_FINITE = 2  # global grad norm is finite
HEALTH_GRAD_BOUNDED = 4  # global grad norm <= grad_norm_max
HEALTH_DRIFT_BOUNDED = 8  # |params_new| <= drift_max * (|params_old|+1)
HEALTH_ALL = (
    HEALTH_LOSS_FINITE
    | HEALTH_GRAD_FINITE
    | HEALTH_GRAD_BOUNDED
    | HEALTH_DRIFT_BOUNDED
)

#: The events a recovery.jsonl line may carry, with their REQUIRED keys
#: (the schema :func:`read_recovery_log` round-trips).
RECOVERY_EVENTS: Dict[str, tuple] = {
    "skip": ("time", "event", "iteration", "skipped", "consecutive"),
    "rollback": (
        "time", "event", "iteration", "to_step", "recoveries", "mttr_s",
    ),
    "halt": ("time", "event", "iteration", "recoveries", "reason"),
}


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Bounds for the in-program health word. The defaults are
    deliberately GENEROUS — the word exists to catch divergence (NaN,
    1e18-scale explosions), not to police ordinary optimization noise;
    a healthy run must never trip it (the bitwise ON==OFF pin depends
    on that)."""

    grad_norm_max: float = 1.0e6  # raw (pre-clip) global grad norm
    #   bound — healthy pre-clip norms reach the hundreds at small
    #   scales (measured), divergence shows up at 1e18+/NaN; the bound
    #   sits orders of magnitude above the one and below the other
    param_drift_max: float = 10.0  # per-iteration growth bound:
    #   |p_new| <= param_drift_max * (|p_old| + 1)


@dataclasses.dataclass(frozen=True)
class RecoveryConfig:
    """The host-side escalation ladder's knobs."""

    breach_iters: int = 3  # consecutive skipped iterations = sustained
    #   breach (a single transient skip is already contained by the
    #   in-program guard and should NOT cost a rollback)
    max_rollbacks: int = 3  # bounded retries; the next sustained breach
    #   after the budget is spent HALTS the run with a flight record
    lr_backoff: float = 1.0  # multiply the injected learning rate by
    #   this on every rollback (needs the optimizer built with
    #   inject_lr=True — the trainer does that automatically when this
    #   is != 1.0; on a non-injected opt state the backoff is audited
    #   as unavailable, never silently applied)
    severity_backoff: float = 1.0  # multiply the scenario-schedule
    #   severity scale by this on every rollback (pure host data — no
    #   recompile; 1.0 = off)


def make_health_iteration(iteration, health: HealthConfig):
    """Wrap a training iteration ``(train_state, env_state, obs, key,
    *extra) -> (train_state, env_state, obs, key, metrics)`` with the
    in-program health word and the skip-update guard.

    The wrapper adds two metrics — ``health_ok`` (1.0 when every check
    passed) and ``health_word`` (the bit layout above) — and selects the
    ENTIRE carry (train state incl. optimizer state and step counter,
    env state, obs) back to the pre-iteration values when flagged; only
    the PRNG key always advances, so the next iteration explores a
    different stream instead of bitwise-replaying the poisoned one.
    Pure data-flow: composes with ``jax.vmap`` (per-member flags and
    per-member skips in the population sweeps) and ``make_fused_chunk``
    (flags stack with the chunk metrics — zero extra dispatches).

    On a healthy run ``jnp.where(True, new, old)`` selects ``new``
    exactly, so outputs are bitwise identical to the unwrapped
    iteration (the acceptance pin)."""
    import jax
    import jax.numpy as jnp
    import optax

    gn_max = float(health.grad_norm_max)
    drift_max = float(health.param_drift_max)

    def health_iteration(train_state, env_state, obs, key, *extra):
        new_ts, new_env, new_obs, new_key, metrics = iteration(
            train_state, env_state, obs, key, *extra
        )
        loss_ok = jnp.isfinite(metrics["loss"])
        grad_norm = metrics.get("grad_norm")
        if grad_norm is None:
            # An iteration that reports no grad norm (a custom core)
            # passes the grad checks — present-or-vacuously-true, the
            # loss/drift checks still stand.
            grad_finite = jnp.asarray(True)
            grad_bounded = jnp.asarray(True)
        else:
            grad_finite = jnp.isfinite(grad_norm)
            # NaN <= x is False, so a non-finite norm fails BOTH flags.
            grad_bounded = grad_norm <= jnp.asarray(gn_max, grad_norm.dtype)
        p_old = optax.global_norm(train_state.params)
        p_new = optax.global_norm(new_ts.params)
        drift_ok = jnp.isfinite(p_new) & (
            p_new <= jnp.asarray(drift_max, p_new.dtype) * (p_old + 1.0)
        )
        healthy = loss_ok & grad_finite & grad_bounded & drift_ok

        def select(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(healthy, n, o), new, old
            )

        out_ts = select(new_ts, train_state)
        out_env = select(new_env, env_state)
        out_obs = jnp.where(healthy, new_obs, obs)
        f32 = jnp.float32
        word = (
            loss_ok.astype(f32) * HEALTH_LOSS_FINITE
            + grad_finite.astype(f32) * HEALTH_GRAD_FINITE
            + grad_bounded.astype(f32) * HEALTH_GRAD_BOUNDED
            + drift_ok.astype(f32) * HEALTH_DRIFT_BOUNDED
        )
        metrics = dict(metrics)
        metrics["health_ok"] = healthy.astype(f32)
        metrics["health_word"] = word
        return out_ts, out_env, out_obs, new_key, metrics

    return health_iteration


def wrap_health(iteration, config) -> Any:
    """The ONE health-wrapping seam every trainer shell shares
    (single-run Trainer, SweepTrainer, HeteroSweepTrainer): returns
    ``iteration`` wrapped with the in-program health word when
    ``config.health`` is set, unchanged otherwise. ``config`` is any
    object with the TrainConfig health knobs — a future bound threads
    through here once instead of three copy-pasted sites."""
    if not getattr(config, "health", False):
        return iteration
    return make_health_iteration(
        iteration,
        HealthConfig(
            grad_norm_max=config.health_grad_norm_max,
            param_drift_max=config.health_param_drift_max,
        ),
    )


def fold_recovery_key(key, recoveries: int):
    """Advance a restored PRNG key into the ``recoveries``-th retry
    stream. The rollback restores the checkpoint's key verbatim — and a
    verbatim key would bitwise-replay the exact dispatch sequence that
    diverged. Folding the recovery counter (offset into a reserved tag
    space so it can never collide with the rollout-index folds the
    scenario sampler uses) gives every retry its own stream while
    keeping recovery DETERMINISTIC: retry N from checkpoint C is a pure
    function of (C, N), which is what makes the post-rollback
    trajectory bit-exact reproducible (tests/test_recovery.py)."""
    import jax
    import jax.numpy as jnp

    return jax.random.fold_in(
        jnp.asarray(key), 0x7EC0_0000 + int(recoveries)
    )


def scale_injected_lr(opt_state, factor: float):
    """Scale an ``optax.inject_hyperparams`` learning rate IN the
    optimizer state (pure data — no recompile, the whole point of the
    injected spelling). Returns the new opt state, or None when no
    ``learning_rate`` hyperparameter leaf exists (a plain
    ``optax.adam(lr)`` bakes the rate into the compiled program — the
    caller audits the backoff as unavailable instead of silently
    no-opping)."""
    import jax

    found = []

    def visit(path, leaf):
        for entry in path:
            name = getattr(entry, "key", getattr(entry, "name", None))
            if name == "learning_rate":
                found.append(True)
                return leaf * factor
        return leaf

    scaled = jax.tree_util.tree_map_with_path(visit, opt_state)
    return scaled if found else None


def nonfinite_flag_count(host_metrics: Dict[str, Any]) -> int:
    """Skipped-update count in a drained (host-side numpy) metrics
    tree: the number of ``health_ok`` entries below 0.5, across every
    axis (iterations x population members). 0 when health is off."""
    flags = host_metrics.get("health_ok")
    if flags is None:
        return 0
    return int((np.asarray(flags, dtype=np.float64) < 0.5).sum())


def record_health_flags(host_metrics: Dict[str, Any]) -> int:
    """THE drain-seam hook every driver shares (single-run trainer,
    SweepTrainer, HeteroSweepTrainer): count this drain's skipped
    updates into ``train_skipped_updates_total``. Host-side only —
    the metrics are already numpy here (post ``device_get``)."""
    skipped = nonfinite_flag_count(host_metrics)
    if skipped:
        from marl_distributedformation_tpu.obs.metrics import get_registry

        get_registry().counter("train_skipped_updates_total").inc(skipped)
    return skipped


class RecoveryLadder:
    """The host-side escalation ladder, fed per-iteration health flags
    at the drain seam.

    State machine (docs/recovery.md):

    - ``observe`` walks the drained flags in iteration order; a healthy
      iteration resets the consecutive-breach counter, an unhealthy one
      advances it. Crossing ``breach_iters`` is a SUSTAINED breach:
      verdict ``"rollback"`` while the retry budget lasts, ``"halt"``
      after. Anything short of that is ``"ok"`` (the in-program guard
      already contained it; a ``skip`` audit line still lands).
    - The trainer performs the rollback (it owns the state) and calls
      :meth:`note_rollback` with the measured MTTR; :meth:`note_halt`
      latches the terminal state.
    - Every transition appends one line to ``recovery.jsonl`` and lands
      in the merged metrics namespace (``train_skipped_updates_total``,
      ``train_divergence_events_total``, ``train_recoveries_total``,
      ``train_recovery_mttr_seconds`` histogram, ``train_halted``).
      Rollbacks and halts additionally dump a flight record.
    """

    def __init__(
        self, config: RecoveryConfig, log_dir: str | Path
    ) -> None:
        self.config = config
        self.log_path = Path(log_dir) / RECOVERY_LOG
        # One file per PROCESS: the ladder's counters start at zero, so
        # appending to a previous run's history would produce a log its
        # own validator rejects (counter "jumping" back to 1, events
        # after a terminal halt). A resumed run rotates the old history
        # aside — preserved for forensics, invisible to the checker.
        if self.log_path.exists() and self.log_path.stat().st_size > 0:
            rotated = self.log_path.with_name(
                f"{RECOVERY_LOG}.{int(time.time() * 1000)}"
            )
            try:
                self.log_path.replace(rotated)
            except OSError:
                pass  # worst case: the checker sees a mixed file
        self.recoveries = 0
        self.skipped_total = 0
        self.breaches = 0
        self.halted = False
        self._consecutive = 0
        # The path the last rollback restored — cleared by the first
        # fully-healthy observation after it. If a SECOND rollback finds
        # this same file still newest, the file itself is the poison
        # (finite-but-diverged params a grad bomb slipped past the
        # non-finite write gate) and the trainer quarantines it before
        # walking further back.
        self.last_rollback_path: Optional[str] = None
        # Post-rollback probation: detection lags one chunk, so the
        # FIRST post-rollback save would land before that chunk's flags
        # drain — if the restored state is itself poisoned (a finite
        # grad bomb that beat the non-finite gate into the newest
        # checkpoint), that save mints a fresh poisoned file at a newer
        # step and the quarantine-on-retarget walk never converges
        # (observed live). Probation holds until a fully-healthy chunk
        # proves the restore stuck.
        self._probation = False

    @property
    def suspect(self) -> bool:
        """True while the most recent observation ended unhealthy OR a
        rollback is still unproven (probation). The trainer gates
        checkpoint SUBMISSION on this: a finite-but-diverged state
        (grad bomb) passes the non-finite write gate, and writing one
        per chunk would hand every rollback a fresh copy of the poison
        at an ever-newer step — the quarantine-on-retarget walk only
        converges when the suspect window writes nothing."""
        return (self._consecutive > 0 or self._probation) and (
            not self.halted
        )

    # -- the drain-seam feed ---------------------------------------------

    def observe(
        self,
        ok_flags: Any,
        words: Any = None,
        first_iteration: int = 0,
    ) -> str:
        """One drained batch of per-iteration flags (host numpy, in
        iteration order); returns the verdict: ``"ok"`` | ``"rollback"``
        | ``"halt"``."""
        from marl_distributedformation_tpu.obs.metrics import get_registry

        if self.halted:
            return "halt"
        ok = np.asarray(ok_flags, dtype=np.float64).reshape(-1)
        skipped = int((ok < 0.5).sum())
        self.skipped_total += skipped
        registry = get_registry()
        if skipped:
            registry.counter("train_skipped_updates_total").inc(skipped)
        breach = False
        for value in ok:
            if value >= 0.5:
                self._consecutive = 0
            else:
                self._consecutive += 1
                if self._consecutive >= self.config.breach_iters:
                    breach = True
        registry.gauge("train_consecutive_unhealthy").set(
            float(self._consecutive)
        )
        if skipped == 0 and self._consecutive == 0:
            # Healthy progress: the last rollback target held — lift
            # probation and forget the retarget memo.
            self.last_rollback_path = None
            self._probation = False
            return "ok"
        word_min: Optional[int] = None
        if words is not None:
            w = np.asarray(words, dtype=np.float64).reshape(-1)
            if w.size:
                word_min = int(w.min())
        self._append({
            "event": "skip",
            "iteration": int(first_iteration),
            "skipped": skipped,
            "consecutive": int(self._consecutive),
            "health_word_min": word_min,
        })
        if not breach:
            return "ok"
        self.breaches += 1
        registry.counter("train_divergence_events_total").inc()
        if self.recoveries >= self.config.max_rollbacks:
            return "halt"
        return "rollback"

    # -- transitions (the trainer calls these after acting) ---------------

    def note_rollback(
        self,
        to_step: int,
        path: Optional[str],
        mttr_s: float,
        iteration: int,
        lr_scale: Optional[float] = None,
        severity_scale: Optional[float] = None,
    ) -> None:
        from marl_distributedformation_tpu.obs import (
            get_registry,
            get_tracer,
        )

        self.recoveries += 1
        self._consecutive = 0
        self._probation = True  # saves stay suspended until a healthy
        #   chunk proves the restore stuck (see __init__)
        self.last_rollback_path = str(path) if path is not None else None
        registry = get_registry()
        registry.counter("train_recoveries_total").inc()
        registry.histogram("train_recovery_mttr_seconds").observe(
            float(mttr_s)
        )
        record = {
            "event": "rollback",
            "iteration": int(iteration),
            "to_step": int(to_step),
            "recoveries": int(self.recoveries),
            "mttr_s": round(float(mttr_s), 4),
            "checkpoint": str(path) if path is not None else None,
            "lr_scale": lr_scale,
            "severity_scale": severity_scale,
        }
        get_tracer().incident("train_rollback", **record)
        self._append(record)

    def note_halt(self, iteration: int, reason: str) -> None:
        from marl_distributedformation_tpu.obs import (
            get_registry,
            get_tracer,
        )

        self.halted = True
        get_registry().gauge("train_halted").set(1.0)
        record = {
            "event": "halt",
            "iteration": int(iteration),
            "recoveries": int(self.recoveries),
            "reason": str(reason)[:300],
        }
        get_tracer().incident("train_divergence_halt", **record)
        self._append(record)

    # -- the audit log -----------------------------------------------------

    def _append(self, record: Dict[str, Any]) -> None:
        line = {"time": round(time.time(), 3), **record}
        try:
            self.log_path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.log_path, "a") as f:
                f.write(json.dumps(line) + "\n")
        except OSError:
            pass  # the audit trail must never become the failure mode


def read_recovery_log(path: str | Path) -> List[Dict[str, Any]]:
    """Parse + validate ``recovery.jsonl``: every line JSON, every event
    known, every required key present (:data:`RECOVERY_EVENTS` is the
    schema). Raises ``ValueError`` naming the first offending line —
    the round-trip contract tests/test_recovery.py pins and the chaos
    invariant checker builds on. A missing file is an empty history."""
    path = Path(path)
    if not path.exists():
        return []
    records: List[Dict[str, Any]] = []
    for i, raw in enumerate(path.read_text().splitlines()):
        if not raw.strip():
            continue
        try:
            rec = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"{path}:{i + 1}: unparseable recovery line: {e}"
            ) from e
        event = rec.get("event")
        required = RECOVERY_EVENTS.get(event)
        if required is None:
            raise ValueError(
                f"{path}:{i + 1}: unknown recovery event {event!r} "
                f"(known: {sorted(RECOVERY_EVENTS)})"
            )
        missing = [k for k in required if k not in rec]
        if missing:
            raise ValueError(
                f"{path}:{i + 1}: {event!r} line is missing required "
                f"key(s) {missing}"
            )
        records.append(rec)
    return records
