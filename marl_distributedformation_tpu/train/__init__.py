"""Training drivers."""

from marl_distributedformation_tpu.train.trainer import (  # noqa: F401
    TrainConfig,
    Trainer,
    make_fused_chunk,
    make_ppo_iteration,
)
from marl_distributedformation_tpu.train.recovery import (  # noqa: F401
    HealthConfig,
    RecoveryConfig,
    RecoveryLadder,
    fold_recovery_key,
    make_health_iteration,
    read_recovery_log,
    record_health_flags,
    wrap_health,
)
from marl_distributedformation_tpu.train.sweep import (  # noqa: F401
    SweepTrainer,
)
from marl_distributedformation_tpu.train.curriculum import (  # noqa: F401
    Curriculum,
    CurriculumStage,
    HeteroTrainer,
    curriculum_from_cfg,
    make_hetero_iteration,
    sample_stage_counts,
)
from marl_distributedformation_tpu.train.hetero_sweep import (  # noqa: F401
    HeteroSweepTrainer,
)
from marl_distributedformation_tpu.train.sebulba import (  # noqa: F401
    ParamBus,
    SebulbaDriver,
    TransferQueue,
    assign_gate_device,
    partition_devices,
)
