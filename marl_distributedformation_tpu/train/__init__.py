"""Training drivers."""

from marl_distributedformation_tpu.train.trainer import (  # noqa: F401
    TrainConfig,
    Trainer,
)
