"""Sebulba driver: the split acting/learning architecture (docs/sebulba.md).

The Podracer paper's SECOND architecture (PAPERS.md, arXiv:2104.06272)
next to Anakin: the local device pool is partitioned into an **actor
slice** that runs the compiled rollout program against a params snapshot
and a **learner slice** that drains K trajectory batches per fused
update chunk. The two meet only at host seams — a bounded
:class:`~.queues.TransferQueue` forward (backpressure + seq /
params-version stamps) and a single-slot :class:`~.queues.ParamBus`
back (latest-wins atomic swap at the actor dispatch boundary).

The functional split mirrors :func:`train.make_ppo_iteration` EXACTLY —
same key threading (``key, k_roll, k_update = split(key, 3)``), same op
sequence, just cut at the rollout/update boundary — so depth-1 lockstep
Sebulba (:meth:`SebulbaDriver.run_lockstep_iteration`) is bitwise
identical to the Anakin host loop at identical seeds
(tests/test_sebulba.py pins it). Neither slice program donates its
arguments: the ParamBus slot holds the same device buffers the learner's
``train_state.params`` point at (and the actor snapshots), so a donating
learner jit would invalidate the published weights mid-rollout — the
use-after-donation class utils/checkpoint.own_restored exists for, here
avoided by construction. That costs one extra params-sized buffer per
slice versus Anakin's donated carry; the un-contended gate/adversary
latency is what it buys (ROADMAP item 1).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from marl_distributedformation_tpu.algo import (
    MinibatchData,
    PPOConfig,
    collect_rollout,
    compute_gae,
    ppo_update,
)
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.obs.metrics import get_registry
from marl_distributedformation_tpu.train.recovery import (
    HEALTH_DRIFT_BOUNDED,
    HEALTH_GRAD_BOUNDED,
    HEALTH_GRAD_FINITE,
    HEALTH_LOSS_FINITE,
    HealthConfig,
    record_health_flags,
)
from marl_distributedformation_tpu.train.sebulba.queues import (
    ParamBus,
    TransferItem,
    TransferQueue,
)
from marl_distributedformation_tpu.train.trainer import TrainConfig, Trainer
from marl_distributedformation_tpu.utils import (
    AsyncCheckpointWriter,
    MetricsLogger,
    Throughput,
)
from marl_distributedformation_tpu.utils import profiling


def partition_devices(
    actor_devices: int = 1,
) -> Tuple[Tuple[Any, ...], Tuple[Any, ...]]:
    """Split ``jax.local_devices()`` into (actor_slice, learner_slice).

    The first ``actor_devices`` devices act, the rest learn; at least one
    device is always left for the learner. A single-device host (the CPU
    default without ``xla_force_host_platform_device_count``) returns the
    SAME device in both slices — the lanes still pipeline through the
    queue, they just time-share silicon (and every cross-slice
    ``device_put`` is skipped: same-device placement is a no-op that
    would only add dispatch noise)."""
    devices = tuple(jax.local_devices())
    if len(devices) == 1:
        return devices, devices
    n = max(1, min(int(actor_devices), len(devices) - 1))
    return devices[:n], devices[n:]


def assign_gate_device(actor_devices: int = 1):
    """The promotion gate's OWN slice under the sebulba partition.

    Prefers a device neither the actor slice nor the learner's primary
    (``learner_slice[0]`` — the single device the fused update chunk
    dispatches on) occupies, so gate evals never contend with either
    lane; on a pool too small to spare one it falls back to the tail of
    the learner slice (an honest time-share, recorded as such by the
    supervisor's ``gate_device``)."""
    actor_slice, learner_slice = partition_devices(actor_devices)
    busy = {id(d) for d in actor_slice} | {id(learner_slice[0])}
    free = [d for d in jax.local_devices() if id(d) not in busy]
    return free[-1] if free else learner_slice[-1]


def make_actor_rollout(
    apply_fn: Any,
    env_params: EnvParams,
    ppo: PPOConfig,
    env_step_fn: Any = None,
    scenario_step_fn: Any = None,
):
    """The acting half of :func:`train.make_ppo_iteration` — byte-for-
    byte its rollout section, with the SAME key threading: the iteration
    key splits into ``(key, k_roll, k_update)`` here, ``k_roll`` drives
    the rollout, and ``k_update`` rides the trajectory payload to the
    learner so the update consumes exactly the key Anakin would have —
    the hinge of the bitwise lockstep-parity pin.

    ``(params, env_state, obs, key, *scenario_args) ->
    (env_state, last_obs, key, k_update, batch, last_value)``"""

    def actor_rollout(params, env_state, obs, key, *scenario_args):
        if scenario_step_fn is not None:
            (scenario_params,) = scenario_args
            step_fn = lambda s, v: scenario_step_fn(s, v, scenario_params)  # noqa: E731
        else:
            step_fn = env_step_fn
        key, k_roll, k_update = jax.random.split(key, 3)
        with jax.named_scope("rollout"):
            env_state, last_obs, batch, last_value = collect_rollout(
                apply_fn,
                params,
                env_state,
                obs,
                k_roll,
                env_params,
                ppo.n_steps,
                env_step_fn=step_fn,
            )
        return env_state, last_obs, key, k_update, batch, last_value

    return actor_rollout


def make_learner_update(
    env_params: EnvParams, ppo: PPOConfig, per_formation: bool = False
):
    """The learning half of :func:`train.make_ppo_iteration` — GAE,
    minibatch reshape, and all PPO epochs, producing the SAME metrics
    dict (rollout metric means, update metrics, reward, episode_dones)
    so a lockstep run's records match Anakin's field-for-field.

    ``(train_state, batch, last_value, k_update) ->
    (train_state, metrics)``"""
    if per_formation:
        n = env_params.num_agents
        update_ppo = dataclasses.replace(
            ppo, batch_size=max(1, ppo.batch_size // n)
        )
        row_shape = (n,)
    else:
        update_ppo = ppo
        row_shape = ()

    def learner_update(train_state, batch, last_value, k_update):
        with jax.named_scope("gae"):
            advantages, returns = compute_gae(
                batch.rewards,
                batch.values,
                batch.dones,
                last_value,
                ppo.gamma,
                ppo.gae_lambda,
            )
        flat = MinibatchData(
            obs=batch.obs.reshape(-1, *row_shape, env_params.obs_dim),
            actions=batch.actions.reshape(
                -1, *row_shape, env_params.act_dim
            ),
            old_log_probs=batch.log_probs.reshape(-1, *row_shape),
            advantages=advantages.reshape(-1, *row_shape),
            returns=returns.reshape(-1, *row_shape),
        )
        with jax.named_scope("ppo_update"):
            train_state, update_metrics = ppo_update(
                train_state, flat, k_update, update_ppo
            )
        metrics = {k: v.mean() for k, v in batch.metrics.items()}
        metrics.update(update_metrics)
        metrics["reward"] = batch.rewards.mean()
        metrics["episode_dones"] = batch.dones[..., 0].sum()
        return train_state, metrics

    return learner_update


def make_learner_health(update, health: HealthConfig):
    """The PR-15 health word, riding the learner unchanged: same four
    flags, same bit layout, same ``jnp.where`` skip-update guard as
    :func:`train.recovery.make_health_iteration` — restricted to the
    state the learner OWNS (``train_state``; env state and obs live on
    the actor slice and were produced by an already-published params
    version, so a flagged update leaves them untouched by design). On a
    healthy run ``jnp.where(True, new, old)`` selects ``new`` exactly,
    preserving the bitwise lockstep-parity pin with health on."""
    import optax

    gn_max = float(health.grad_norm_max)
    drift_max = float(health.param_drift_max)

    def health_update(train_state, batch, last_value, k_update):
        new_ts, metrics = update(train_state, batch, last_value, k_update)
        loss_ok = jnp.isfinite(metrics["loss"])
        grad_norm = metrics.get("grad_norm")
        if grad_norm is None:
            grad_finite = jnp.asarray(True)
            grad_bounded = jnp.asarray(True)
        else:
            grad_finite = jnp.isfinite(grad_norm)
            # NaN <= x is False, so a non-finite norm fails BOTH flags.
            grad_bounded = grad_norm <= jnp.asarray(gn_max, grad_norm.dtype)
        p_old = optax.global_norm(train_state.params)
        p_new = optax.global_norm(new_ts.params)
        drift_ok = jnp.isfinite(p_new) & (
            p_new <= jnp.asarray(drift_max, p_new.dtype) * (p_old + 1.0)
        )
        healthy = loss_ok & grad_finite & grad_bounded & drift_ok
        out_ts = jax.tree_util.tree_map(
            lambda n, o: jnp.where(healthy, n, o), new_ts, train_state
        )
        f32 = jnp.float32
        word = (
            loss_ok.astype(f32) * HEALTH_LOSS_FINITE
            + grad_finite.astype(f32) * HEALTH_GRAD_FINITE
            + grad_bounded.astype(f32) * HEALTH_GRAD_BOUNDED
            + drift_ok.astype(f32) * HEALTH_DRIFT_BOUNDED
        )
        metrics = dict(metrics)
        metrics["health_ok"] = healthy.astype(f32)
        metrics["health_word"] = word
        return out_ts, metrics

    return health_update


def make_learner_chunk(update):
    """Fuse the learner over a whole drained chunk: one ``lax.scan``
    device program consumes K stacked trajectory payloads
    ``(batch, last_value, k_update)`` (leading ``(k,)`` axis) and
    returns per-batch metrics stacked the same way — the learner-slice
    twin of :func:`train.make_fused_chunk`, with the trajectories as xs
    instead of re-rolling them (the actor already did). K is a trace
    constant via the xs shape, so a run's single chunk size compiles
    once (budget-1 receipts per slice)."""

    def learner_chunk(train_state, payload):
        def body(ts, xs):
            batch, last_value, k_update = xs
            ts, metrics = update(ts, batch, last_value, k_update)
            return ts, metrics

        train_state, stacked = jax.lax.scan(body, train_state, payload)
        return train_state, stacked

    return learner_chunk


def _stack_payloads(items: Sequence[TransferItem]):
    """Stack K dequeued payloads along a new leading axis — the
    ``lax.scan`` xs for one learner chunk. Host-side tree_map of
    ``jnp.stack``: on a split pool the leaves are already resident on
    the learner slice (the queue placed them at enqueue), so the stack
    is a device-local concat, not a transfer."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[item.payload for item in items]
    )


class SebulbaDriver(Trainer):
    """Trainer shell for ``TrainConfig.architecture = "sebulba"``.

    Subclasses :class:`Trainer` for everything that is NOT dispatch
    shape — model/optimizer construction, env reset, scenario machinery
    (schedules, samplers, the thread-safe curriculum handoff), the
    checkpoint read/write contract, resume. The Anakin jit the base
    class builds is never dispatched here, so it never compiles, never
    registers in the ledger, and its RetraceGuard stays at 0 — the
    sebulba slices carry their OWN budget-1 guards
    (``actor_guard`` / ``learner_guard``).

    ``fused_chunk`` is reinterpreted as **K**, the batches the learner
    drains per fused update chunk (0 -> 1). Two dispatch surfaces:

    - :meth:`run_lockstep_iteration` — depth-1 synchronous parity mode:
      one thread walks actor -> queue -> learner -> bus, driving the
      REAL transfer plumbing, bitwise identical to Anakin's
      ``run_iteration`` at identical seeds.
    - :meth:`train` — the pipelined mode: a daemon actor thread produces
      rollouts against the freshest published snapshot while the main
      thread drains/updates/publishes; queue backpressure bounds the
      actor's lead, the staleness gate bounds what the learner accepts.
    """

    def __init__(
        self,
        env_params: EnvParams,
        ppo: PPOConfig = PPOConfig(),
        config: TrainConfig = TrainConfig(),
        model: Any = None,
        shard_fn: Any = None,
        scenario_schedule: Any = None,
    ) -> None:
        if shard_fn is not None:
            raise SystemExit(
                "sebulba partitions WHOLE devices into actor/learner "
                "slices; mesh sharding (shard_fn) is Anakin-only — drop "
                "the mesh or use architecture=anakin"
            )
        if config.recovery:
            raise SystemExit(
                "the recovery ladder is Anakin-only for now (its rollback "
                "restores the full carry on one thread; the sebulba "
                "learner does not own env state) — drop recovery or use "
                "architecture=anakin. The in-program health word itself "
                "rides the sebulba learner fine: health=true"
            )
        if config.iters_per_dispatch > 1:
            raise SystemExit(
                "iters_per_dispatch is the Anakin host-loop burst "
                "spelling; sebulba fuses at the learner — set fused_chunk "
                "to K, the batches drained per update chunk"
            )
        super().__init__(
            env_params,
            ppo=ppo,
            config=config,
            model=model,
            shard_fn=None,
            scenario_schedule=scenario_schedule,
        )
        if self._multihost:
            raise SystemExit(
                "sebulba is single-host for now (the transfer queue and "
                "param bus are process-local); run single-process or use "
                "the mesh tier for cross-host scale"
            )
        self.actor_slice, self.learner_slice = partition_devices(
            config.actor_devices
        )
        self._split_slices = (
            self.actor_slice[0] is not self.learner_slice[0]
        )
        self._learner_chunk_k = max(1, self._fused_chunk)

        actor_core = make_actor_rollout(
            self.model.apply,
            env_params,
            self.ppo,
            self._env_step_fn,
            self._scenario_step_fn,
        )
        update_core = make_learner_update(
            env_params, self.ppo, self.per_formation
        )
        if config.health:
            update_core = make_learner_health(
                update_core,
                HealthConfig(
                    grad_norm_max=config.health_grad_norm_max,
                    param_drift_max=config.health_param_drift_max,
                ),
            )
        # Per-slice budget-1 guards + ledger attribution: each slice's
        # program is its own census entry under subsystem="sebulba".
        # NO donate_argnums on either program — the ParamBus slot and the
        # actor's in-flight snapshot alias the learner's params buffers,
        # and the async checkpoint writer snapshots the actor-owned env
        # carry; donating any of them is a use-after-free (the memory
        # cost vs Anakin's donated carry is one params/carry copy).
        self.actor_guard = profiling.RetraceGuard(
            "sebulba_actor", max_traces=config.guard_retraces or None
        )
        self.learner_guard = profiling.RetraceGuard(
            "sebulba_learner", max_traces=config.guard_retraces or None
        )
        self._actor_program = profiling.ledgered_jit(
            actor_core,
            self.actor_guard,
            subsystem="sebulba",
            program="sebulba_actor_rollout",
        )
        self._learner_program = profiling.ledgered_jit(
            make_learner_chunk(update_core),
            self.learner_guard,
            subsystem="sebulba",
            program="sebulba_learner_chunk",
        )
        self._queue = TransferQueue(
            config.transfer_queue_depth,
            learner_device=(
                self.learner_slice[0] if self._split_slices else None
            ),
        )
        self._bus = ParamBus(
            actor_device=self.actor_slice[0] if self._split_slices else None
        )
        if self._split_slices:
            # Commit each lane's carry onto its owning slice ONCE, here —
            # jit follows committed inputs, so neither program needs a
            # device= pin and every later dispatch is placement-free.
            self.train_state = jax.device_put(
                self.train_state, self.learner_slice[0]
            )
            self.env_state = jax.device_put(
                self.env_state, self.actor_slice[0]
            )
            self.obs = jax.device_put(self.obs, self.actor_slice[0])
            self.key = jax.device_put(self.key, self.actor_slice[0])
        # Version 0 = the initial (or resumed — super ran _try_resume
        # already) params; the learner bumps and republishes per chunk.
        self._learner_version = 0
        self._bus.publish(self.train_state.params, 0)
        # Host artifacts for the staleness contract:
        # ``staleness_samples`` records every DEQUEUED batch's
        # (learner_version - stamped_version) — including ones the gate
        # then drops (the p95 gauge's population); ``consumed_staleness``
        # only the batches that reached an update (the chaos
        # bounded-staleness invariant's population, which must never
        # exceed the bound); ``consumed_versions`` the consumed version
        # sequence the monotonicity invariant checks.
        self.staleness_samples: collections.deque = collections.deque(
            maxlen=65536
        )
        self.consumed_staleness: collections.deque = collections.deque(
            maxlen=65536
        )
        self.consumed_versions: List[int] = []
        self.stale_dropped = 0
        self._actor_thread: Optional[threading.Thread] = None
        self._actor_error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._actor_heartbeat = None
        self._learner_heartbeat = None
        self._actor_meter = Throughput()

    # ------------------------------------------------------------------
    # Anakin dispatch surfaces are fenced off (dispatching them would
    # compile the fused Anakin program BESIDE the slice programs and
    # break the per-slice budget-1 receipts).
    # ------------------------------------------------------------------

    def run_iteration(self) -> Dict[str, float]:
        raise SystemExit(
            "sebulba dispatches via run_lockstep_iteration() (depth-1 "
            "parity mode) or train() (pipelined lanes) — Anakin's "
            "run_iteration() would compile the fused train program "
            "beside the slice programs"
        )

    def run_chunk(self) -> Dict[str, Any]:
        raise SystemExit(
            "sebulba has no Anakin chunk dispatch; fused_chunk is K, the "
            "learner's drain width — use train() or "
            "run_lockstep_iteration()"
        )

    # ------------------------------------------------------------------
    # Lockstep parity mode
    # ------------------------------------------------------------------

    def run_lockstep_iteration(self) -> Dict[str, Any]:
        """One synchronous actor->queue->learner->bus round trip on the
        calling thread, driving the REAL transfer plumbing (seq stamps,
        version stamps, occupancy gauges — everything but concurrency).
        Bitwise identical to Anakin's ``run_iteration()`` at identical
        seeds: same key threading, same op sequence, cut across two
        compiled programs (scan-of-1 at the learner; tests/test_sebulba
        pins params AND per-iteration metrics). Returns the iteration's
        metrics as device scalars (the chunk stack's single row).

        Under an armed chaos plane an enqueue-drop surfaces as an empty
        dict (the rollout happened, nothing was learned) — the host
        counters then advance by the ROLLOUT, not the update, exactly
        like the pipelined mode."""
        self._apply_pending_schedule()
        version, params = self._bus.latest()
        extra = (
            () if self.scenario_params is None else (self.scenario_params,)
        )
        env_state, last_obs, key, k_update, batch, last_value = (
            self._actor_program(
                params, self.env_state, self.obs, self.key, *extra
            )
        )
        self.env_state, self.obs, self.key = env_state, last_obs, key
        self.num_timesteps += self.ppo.n_steps * self.num_envs
        self._vec_steps_since_save += self.ppo.n_steps
        if self._scenario_schedule is not None:
            self._scenario_rollouts += 1
            self._scenario_draws += 1
            self._resample_scenario_params()
        seq = self._queue.put((batch, last_value, k_update), version)
        if seq is None:
            return {}
        item = self._queue.get(timeout_s=5.0)
        if item is None:
            return {}
        staleness = self._learner_version - item.params_version
        self.staleness_samples.append(staleness)
        self.consumed_staleness.append(staleness)
        self.consumed_versions.append(item.params_version)
        self.train_state, stacked = self._learner_program(
            self.train_state, _stack_payloads([item])
        )
        self._learner_version += 1
        self._bus.publish(self.train_state.params, self._learner_version)
        self._dispatches += 1
        get_registry().counter("train_iterations_total").inc()
        return jax.tree_util.tree_map(lambda v: v[0], stacked)

    # ------------------------------------------------------------------
    # Pipelined mode
    # ------------------------------------------------------------------

    def _spawn_actor(self) -> None:
        self._actor_thread = threading.Thread(
            target=self._actor_loop, name="sebulba-actor", daemon=True
        )
        self._actor_thread.start()

    def _restart_actor(self) -> None:
        """LaneWatchdog restart hook: respawn a dead actor thread (the
        carry attributes still hold the last completed rollout's state,
        so the respawn resumes the stream instead of resetting it)."""
        if self._stop.is_set():
            return
        if self._actor_thread is not None and self._actor_thread.is_alive():
            return
        self._actor_error = None
        self._spawn_actor()

    def attach_watchdog(self, watchdog: Any) -> None:
        """Register both lanes with a ``chaos.LaneWatchdog``: heartbeats
        age per rollout / per chunk, a dead actor thread restarts via
        :meth:`_restart_actor`, and a wedged learner (no beat past the
        watchdog's wedge timeout) is surfaced by the watchdog's existing
        escalation — the same supervision contract every other lane
        rides."""
        from marl_distributedformation_tpu.chaos.watchdog import Heartbeat

        self._actor_heartbeat = Heartbeat("sebulba_actor")
        self._learner_heartbeat = Heartbeat("sebulba_learner")
        watchdog.register(
            "sebulba_actor",
            self._actor_heartbeat,
            is_alive=lambda: (
                self._actor_thread is None
                or self._actor_thread.is_alive()
                or self._stop.is_set()
            ),
            restart=self._restart_actor,
        )
        watchdog.register(
            "sebulba_learner",
            self._learner_heartbeat,
            is_alive=lambda: True,  # the learner IS the main thread
            restart=lambda: None,
        )

    def _actor_loop(self) -> None:
        """Producer lane: snapshot the freshest published params, run one
        compiled rollout, enqueue the trajectory. The queue's
        backpressure (a full queue blocks ``put``) is the ONLY pacing —
        the actor never sleeps, never polls the learner. Carry
        attributes (env_state/obs/key) are written only by this thread
        while it runs; the learner thread reads them only after join
        (checkpointing happens at chunk boundaries off the same
        attributes Anakin uses, which is safe because `save` snapshots
        under the learner after the actor parked in `put` or exited)."""
        try:
            while not self._stop.is_set():
                self._apply_pending_schedule()
                version, params = self._bus.latest()
                extra = (
                    ()
                    if self.scenario_params is None
                    else (self.scenario_params,)
                )
                env_state, last_obs, key, k_update, batch, last_value = (
                    self._actor_program(
                        params, self.env_state, self.obs, self.key, *extra
                    )
                )
                self.env_state, self.obs, self.key = (
                    env_state,
                    last_obs,
                    key,
                )
                self.num_timesteps += self.ppo.n_steps * self.num_envs
                self._vec_steps_since_save += self.ppo.n_steps
                if self._scenario_schedule is not None:
                    self._scenario_rollouts += 1
                    self._scenario_draws += 1
                    self._resample_scenario_params()
                self._queue.put((batch, last_value, k_update), version)
                if self._queue.closed:
                    return
                if self._actor_heartbeat is not None:
                    self._actor_heartbeat.beat()
                self._actor_meter.tick(
                    self.ppo.n_steps * self.config.num_formations
                )
                get_registry().gauge("actor_env_steps_per_sec").set(
                    self._actor_meter.rate()
                )
        except BaseException as exc:  # surfaced by the learner loop
            self._actor_error = exc
            self._queue.close()

    def _collect_chunk(
        self, k: int, timeout_s: float = 60.0
    ) -> Optional[List[TransferItem]]:
        """Drain K fresh-enough batches for one learner chunk. Batches
        staler than ``max_param_staleness`` learner updates are dropped
        here (counted, never trained on) — which makes the bounded-
        staleness contract structural: every CONSUMED batch satisfies
        it. Returns None when the stream ended (queue closed / actor
        dead / timeout) before K arrived."""
        items: List[TransferItem] = []
        deadline = time.monotonic() + timeout_s
        registry = get_registry()
        while len(items) < k:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            item = self._queue.get(timeout_s=min(1.0, remaining))
            if item is None:
                if self._queue.closed or not (
                    self._actor_thread and self._actor_thread.is_alive()
                ):
                    return None
                continue
            staleness = self._learner_version - item.params_version
            self.staleness_samples.append(staleness)
            registry.gauge("param_staleness_updates").set(float(staleness))
            if staleness > self.config.max_param_staleness:
                self.stale_dropped += 1
                registry.counter("sebulba_stale_dropped_total").inc()
                continue
            self.consumed_staleness.append(staleness)
            self.consumed_versions.append(item.params_version)
            items.append(item)
        return items

    def train(self) -> Dict[str, float]:
        """Pipelined training: actor thread produces, this thread drains
        K batches per fused learner chunk, updates, publishes. Metrics
        records are per-iteration like Anakin's fused drain; checkpoints
        land at chunk boundaries on the background writer. Stops at the
        timestep budget (counted at the ACTOR — env interaction is the
        budget's unit; trailing in-queue batches past the budget are
        left unconsumed, matching on-policy semantics)."""
        logger = MetricsLogger(
            self.log_dir,
            run_name=self.config.name,
            use_wandb=self.config.use_wandb,
            use_tensorboard=self.config.use_tensorboard,
        )
        learner_meter = Throughput()
        writer = (
            AsyncCheckpointWriter(
                keep_last_n=self.config.keep_last_n,
                protect=self._protected_paths,
            )
            if self.config.checkpoint
            else None
        )
        registry = get_registry()
        k = self._learner_chunk_k
        per_iter = self.ppo.n_steps * self.num_envs
        last_record: Dict[str, float] = {}
        iteration = 0
        self._stop.clear()
        self._spawn_actor()
        try:
            while self.num_timesteps < self.total_timesteps:
                items = self._collect_chunk(k)
                if items is None:
                    break
                steps_before = self.num_timesteps
                self.train_state, stacked = self._learner_program(
                    self.train_state, _stack_payloads(items)
                )
                self._learner_version += 1
                self._bus.publish(
                    self.train_state.params, self._learner_version
                )
                if self._learner_heartbeat is not None:
                    self._learner_heartbeat.beat()
                self._dispatches += 1
                registry.counter("train_iterations_total").inc(k)
                host = jax.device_get(stacked)
                record_health_flags(host)
                learner_meter.tick(k)
                registry.gauge("learner_steps_per_sec").set(
                    learner_meter.rate()
                )
                registry.gauge("train_compiles").set(
                    self.actor_guard.count + self.learner_guard.count
                )
                for i in range(k):
                    if (iteration + i + 1) % self.config.log_interval:
                        continue
                    record = {name: float(v[i]) for name, v in host.items()}
                    record["learner_steps_per_sec"] = learner_meter.rate()
                    record["actor_env_steps_per_sec"] = (
                        self._actor_meter.rate()
                    )
                    record["param_staleness_updates"] = float(
                        self._learner_version - 1 - items[i].params_version
                    )
                    logger.log(record, steps_before + (i + 1) * per_iter)
                    last_record = record
                iteration += k
                if (
                    writer is not None
                    and self._vec_steps_since_save >= self.config.save_freq
                ):
                    self.save_async(writer)
        finally:
            self._stop.set()
            self._queue.close()
            if self._actor_thread is not None:
                self._actor_thread.join(timeout=30.0)
            if writer is not None:
                self.save_async(writer)
                writer.close_quietly()
            logger.close()
        if self._actor_error is not None:
            raise RuntimeError(
                "sebulba actor lane died"
            ) from self._actor_error
        return last_record

    # ------------------------------------------------------------------
    # Bench / campaign accessors
    # ------------------------------------------------------------------

    def occupancy_p95(self) -> float:
        """p95 transfer-queue occupancy over the run's enqueue samples
        (0.0 before any traffic)."""
        if not self._queue.occupancy_samples:
            return 0.0
        return float(
            np.percentile(np.asarray(self._queue.occupancy_samples), 95)
        )

    def staleness_p95(self) -> float:
        """p95 params-staleness (in learner updates) over every batch
        the learner SAW (consumed or staleness-dropped)."""
        if not self.staleness_samples:
            return 0.0
        return float(
            np.percentile(np.asarray(self.staleness_samples), 95)
        )

    @property
    def transfer_queue(self) -> TransferQueue:
        return self._queue

    @property
    def param_bus(self) -> ParamBus:
        return self._bus
