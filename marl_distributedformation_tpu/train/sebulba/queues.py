"""Host-side transfer plumbing for the Sebulba lane (docs/sebulba.md).

Two primitives connect the actor slice to the learner slice:

- :class:`TransferQueue` — a bounded FIFO of fixed-shape trajectory
  batches. ``put`` blocks when the queue is full (backpressure: the
  actor can never run more than ``depth`` rollouts ahead of the
  learner), stamps every item with a monotone ``seq`` and the
  ``params_version`` the rollout was acted with, and ``device_put``s
  the payload onto the learner slice at ENQUEUE time — an async
  device-to-device copy dispatched off the learner's critical path, so
  the drain never pays the transfer. The consume side carries a seq
  guard: a redelivered item (the chaos ``sebulba.dequeue`` seam
  simulates a retry bug by re-queuing the item it just handed out) is
  absorbed and counted, never consumed twice — the invariant
  ``chaos.check_no_duplicate_consume`` pins over ``consumed_seqs``.

- :class:`ParamBus` — the single-slot, latest-wins params channel back.
  ``publish`` atomically swaps the slot under a lock and ignores
  non-monotone versions (latest wins by construction); ``latest`` is
  the atomic read the actor performs at its dispatch boundary. The
  publish seam places the params onto the actor slice — the
  once-per-version placement event rule 16 sanctions, so actor
  dispatches reuse device-resident weights. A ``raise`` armed on
  ``sebulba.param_publish`` drops the publish (the stale-params chaos
  effect): actors keep acting on the previous version until the next
  one lands, and the learner's staleness gate bounds the damage.

Both ends record into the merged Prometheus namespace at host seams
only (``transfer_queue_occupancy``, ``param_bus_version``, drop /
duplicate counters) and keep small host-side artifact lists
(``consumed_seqs``, ``occupancy_samples``) the chaos invariants and
bench percentiles are computed from.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, List, NamedTuple, Optional, Tuple

from marl_distributedformation_tpu.chaos.plane import (
    InjectedFault,
    fault_point,
)
from marl_distributedformation_tpu.obs.metrics import get_registry

#: Artifact ring bound: campaigns and bench runs are short, but a
#: long-lived driver must not grow host lists without bound.
_MAX_SAMPLES = 65536


class TransferItem(NamedTuple):
    """One queued trajectory batch: ``seq`` is the queue's monotone
    enqueue stamp, ``params_version`` the :class:`ParamBus` version the
    actor snapshot carried, ``payload`` the device tree
    ``(batch, last_value, k_update)``."""

    seq: int
    params_version: int
    payload: Any


class TransferQueue:
    """Bounded host-side queue between the actor and learner lanes."""

    def __init__(
        self,
        depth: int,
        learner_device: Any = None,
        name: str = "transfer_queue",
    ) -> None:
        if depth < 1:
            raise ValueError(
                f"transfer_queue_depth must be >= 1, got {depth}"
            )
        self.depth = int(depth)
        self.name = name
        self._learner_device = learner_device
        self._items: collections.deque = collections.deque()  # graftlock: guarded-by=_lock
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._next_seq = 0  # graftlock: guarded-by=_lock
        self._last_consumed = -1  # graftlock: guarded-by=_lock
        self._closed = False  # graftlock: guarded-by=_lock
        # Campaign / bench artifacts (host ints only, bounded).
        self.consumed_seqs: collections.deque = collections.deque(
            maxlen=_MAX_SAMPLES
        )
        self.occupancy_samples: collections.deque = collections.deque(
            maxlen=_MAX_SAMPLES
        )
        self.enqueued_total = 0
        self.dropped_total = 0
        self.duplicates_absorbed = 0
        get_registry().gauge(f"{name}_depth").set(float(self.depth))

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def put(
        self,
        payload: Any,
        params_version: int,
        timeout_s: Optional[float] = None,
    ) -> Optional[int]:
        """Enqueue one trajectory batch; blocks while the queue is full
        (the backpressure contract). Returns the assigned seq, or None
        when the batch was dropped (queue closed, timeout expired, or
        the ``sebulba.enqueue`` chaos seam fired — a dropped batch is a
        seq GAP downstream, never a duplicate)."""
        # Both conditions share self._lock; acquiring the lock directly
        # keeps every guarded write visibly inside `with self._lock:`
        # (the graftlock contract) while wait/notify still work — a
        # Condition's wait releases and reacquires its backing lock.
        with self._lock:
            while len(self._items) >= self.depth and not self._closed:
                if not self._not_full.wait(timeout=timeout_s):
                    return None
            if self._closed:
                return None
            seq = self._next_seq
            self._next_seq += 1
        try:
            # Chaos seam (chaos/plane.py): an armed 'raise' is the DROP
            # effect — the batch vanishes in transfer, the seq is spent.
            fault_point("sebulba.enqueue")
        except InjectedFault:
            self.dropped_total += 1
            get_registry().counter(
                "sebulba_dropped_batches_total"
            ).inc()
            return None
        if self._learner_device is not None:
            # Device-to-device placement onto the learner slice, HERE at
            # the enqueue seam: jax dispatches the copy asynchronously,
            # so it overlaps the actor's next rollout instead of
            # stalling the learner's drain (the off-critical-path
            # contract; single-device runs skip it — see the driver).
            import jax

            payload = jax.device_put(payload, self._learner_device)
        with self._lock:
            self._items.append(TransferItem(seq, int(params_version), payload))
            occupancy = len(self._items)
            self._not_empty.notify()
        self.enqueued_total += 1
        self.occupancy_samples.append(occupancy)
        get_registry().gauge(f"{self.name}_occupancy").set(float(occupancy))
        return seq

    def get(self, timeout_s: Optional[float] = None) -> Optional[TransferItem]:
        """Dequeue the next batch; blocks up to ``timeout_s`` (None =
        forever). Returns None on timeout or when the queue is closed
        and drained. Redelivered items (seq already consumed) are
        absorbed here — the consume-twice guard."""
        while True:
            item = self._pop(timeout_s)
            if item is None:
                return None
            try:
                # Chaos seam: an armed 'raise' is the DUPLICATE effect —
                # the item is re-queued at the head (a redelivery bug's
                # shape) while this delivery proceeds; the seq guard
                # below absorbs the replay on the next get.
                fault_point("sebulba.dequeue")
            except InjectedFault:
                with self._lock:
                    self._items.appendleft(item)
                    self._not_empty.notify()
            with self._lock:
                if item.seq <= self._last_consumed:
                    duplicate = True
                else:
                    duplicate = False
                    self._last_consumed = item.seq
            if duplicate:
                self.duplicates_absorbed += 1
                get_registry().counter(
                    "sebulba_duplicates_absorbed_total"
                ).inc()
                continue
            self.consumed_seqs.append(item.seq)
            get_registry().gauge(f"{self.name}_occupancy").set(
                float(len(self))
            )
            return item

    def _pop(self, timeout_s: Optional[float]) -> Optional[TransferItem]:
        with self._lock:
            while not self._items:
                if self._closed:
                    return None
                if not self._not_empty.wait(timeout=timeout_s):
                    return None
            item = self._items.popleft()
            self._not_full.notify()
            return item

    def close(self) -> None:
        """Wake every blocked producer/consumer; puts fail from here on,
        gets drain the remaining items then return None."""
        with self._lock:
            self._closed = True
            self._not_full.notify_all()
            self._not_empty.notify_all()


class ParamBus:
    """Single-slot, latest-wins params channel from learner to actors."""

    def __init__(self, actor_device: Any = None) -> None:
        self._actor_device = actor_device
        self._lock = threading.Lock()
        self._fresh = threading.Condition(self._lock)
        self._version = -1  # graftlock: guarded-by=_lock
        self._params: Any = None  # graftlock: guarded-by=_lock
        self.publishes_dropped = 0
        self.versions_published: List[int] = []

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def publish(self, params: Any, version: int) -> bool:
        """Atomic slot swap. Returns False when the publish was dropped:
        by the ``sebulba.param_publish`` chaos seam (the stale-params
        effect — actors keep the previous version) or because a newer
        version already holds the slot (latest wins; version regression
        is structurally impossible at the actor)."""
        try:
            fault_point("sebulba.param_publish")
        except InjectedFault:
            self.publishes_dropped += 1
            get_registry().counter(
                "sebulba_param_publish_dropped_total"
            ).inc()
            return False
        if self._actor_device is not None:
            # Once-per-version placement onto the actor slice — the
            # swap-seam home rule 16 sanctions for device_put; every
            # actor dispatch then reuses the device-resident weights.
            import jax

            params = jax.device_put(params, self._actor_device)
        with self._lock:
            if version <= self._version:
                return False
            self._params = params
            self._version = int(version)
            if len(self.versions_published) < _MAX_SAMPLES:
                self.versions_published.append(self._version)
            self._fresh.notify_all()
        get_registry().gauge("param_bus_version").set(float(version))
        return True

    def latest(self) -> Tuple[int, Any]:
        """The atomic read at the actor dispatch boundary: the newest
        ``(version, params)`` pair, swapped in one lock acquisition."""
        with self._lock:
            return self._version, self._params

    def wait_version(
        self, min_version: int, timeout_s: Optional[float] = None
    ) -> bool:
        """Block until the slot holds at least ``min_version`` (the
        actor's staleness backstop when publishes are being dropped)."""
        with self._lock:
            return self._fresh.wait_for(
                lambda: self._version >= min_version, timeout=timeout_s
            )
