"""Sebulba lane: split acting from learning (docs/sebulba.md).

The Podracer paper's second architecture next to Anakin — an actor
slice runs the compiled rollout program against published params
snapshots, a learner slice drains K trajectory batches per fused update
chunk, and hardened host-side plumbing (:class:`TransferQueue` /
:class:`ParamBus`) connects them. Selected by
``TrainConfig.architecture = "sebulba"``.
"""

from marl_distributedformation_tpu.train.sebulba.driver import (
    SebulbaDriver,
    assign_gate_device,
    make_actor_rollout,
    make_learner_chunk,
    make_learner_health,
    make_learner_update,
    partition_devices,
)
from marl_distributedformation_tpu.train.sebulba.queues import (
    ParamBus,
    TransferItem,
    TransferQueue,
)

__all__ = [
    "ParamBus",
    "SebulbaDriver",
    "TransferItem",
    "TransferQueue",
    "assign_gate_device",
    "make_actor_rollout",
    "make_learner_chunk",
    "make_learner_health",
    "make_learner_update",
    "partition_devices",
]
