"""Population training: K independent PPO runs in ONE jitted program.

The reference's stack trains one policy per process — a seed sweep is K
sequential SB3 invocations (reference vectorized_env.py:112-137 has no
sweep story at all). Here the whole training iteration
(``make_ppo_iteration``) is ``vmap``-ed over a leading seed axis: policy
params, optimizer state, env state, and PRNG streams all carry a ``(K,
...)`` population dimension, and XLA compiles one program that advances
every member per dispatch.

TPU mapping: population members are fully independent, so sharding the
seed axis over the mesh (``mesh={dp: D}``) is embarrassingly parallel —
XLA inserts ZERO collectives and each chip trains ``K/D`` members. This
turns one chip's tuned 4096-formation throughput into a multi-chip
hyperparameter/seed search with perfect scaling, which is the idiomatic
TPU answer to "train many policies": no multiprocessing, no per-process
checkpoints to reconcile, one metrics stream. Multi-host (round 4):
every process initializes only its own member block (per-host
construction, the ``parallel.global_from_local`` pattern), the training
step runs SPMD over the global mesh, and checkpoint IO allgathers the
population to the coordinator — pinned by a real two-process test
(tests/test_multiprocess.py).

Seed semantics: member ``i`` uses root key ``PRNGKey(config.seed + i)``
— bit-identical to a single :class:`Trainer` constructed with
``seed=config.seed + i`` (pinned by ``tests/test_sweep.py``), so a sweep
is exactly K reference-parity runs, just fused.

Hyperparameter search: pass ``learning_rates`` (length K) to give every
member its own learning rate in the same single program. The optimizer is
wrapped in ``optax.inject_hyperparams`` so the rate lives in the
OPTIMIZER STATE (an array leaf the vmap batches) rather than the
transform closure — one shared ``tx`` serves the whole population. These
members' checkpoints carry params only (their opt_state tree differs from
the single-run optimizer's; the resume path re-estimates Adam moments,
same as SB3-imported checkpoints).

Resume: ``resume=true`` restores the latest ``sweep_state_{steps}_steps``
population checkpoint — the full batched learner state (params, optimizer
moments AND injected per-member rates), member PRNG streams, env state,
and progress — and continues bit-identically to an uninterrupted run
(pinned by ``tests/test_sweep.py``). Operationally critical on hardware
that can vanish mid-run for hours (the tunneled-TPU reality this repo
benches on).

Anakin population mode (round 6): ``fused_chunk=K`` compiles K whole
vmapped population iterations into ONE ``lax.scan`` program (the
single-run trainer's fused-scan shape, docs/training.md), so the host
dispatch overhead that used to be paid per population iteration is paid
once per chunk. Per-member metrics come back stacked
``(fused_chunk, num_seeds, ...)`` and drain in one batched ``device_get``
per chunk, double-buffered against the next chunk's execution;
population checkpoints (every member file + the sweep_state anchor)
write on a background thread off a device-side snapshot, at chunk
boundaries — chunk boundary == checkpoint boundary == bit-exact resume
boundary (pinned by ``tests/test_fused_sweep.py``). The old
``iters_per_dispatch`` reduced-metrics burst is retired for sweeps.
"""

from __future__ import annotations

import functools
import json
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax.training.train_state import TrainState

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.envs import spec_for_params
from marl_distributedformation_tpu.jax_compat import shard_map
from marl_distributedformation_tpu.models import MLPActorCritic
from marl_distributedformation_tpu.train.recovery import record_health_flags
from marl_distributedformation_tpu.train.trainer import (
    TrainConfig,
    default_total_timesteps,
    fill_ent_schedule,
    make_fused_chunk,
    make_ppo_iteration,
)
from marl_distributedformation_tpu.utils import (
    AsyncCheckpointWriter,
    MetricsLogger,
    Throughput,
    device_snapshot,
    latest_checkpoint,
    latest_sweep_state,
    own_restored,
    repo_root,
    save_checkpoint,
    save_sweep_state,
)
from marl_distributedformation_tpu.utils import profiling
from marl_distributedformation_tpu.utils.checkpoint import (
    _write_atomic,
    checkpoint_path,
    sweep_state_path,
)

Array = jax.Array


class SweepTrainer:
    """K-seed population PPO under one jit.

    Args:
      env_params / ppo / config: as :class:`Trainer`; every member trains
        the full ``total_timesteps`` budget at identical hyperparameters.
      num_seeds: population size K.
      model: policy module shared across members (fresh params per member).
      mesh: optional ``jax.sharding.Mesh`` whose ``'dp'`` axis shards the
        seed axis (K must divide by it). Members never communicate, so
        this composes with any mesh the single-run trainer accepts.
      learning_rates: optional length-K array — per-member learning rates
        (population hyperparameter search). None keeps every member at
        ``ppo.learning_rate`` with the exact single-run optimizer.
    """

    def __init__(
        self,
        env_params: EnvParams,
        ppo: PPOConfig = PPOConfig(),
        config: TrainConfig = TrainConfig(),
        num_seeds: int = 4,
        model: Any = None,
        mesh: Any = None,
        learning_rates: Any = None,
    ) -> None:
        assert num_seeds >= 1
        self._fused_chunk = max(0, int(config.fused_chunk))
        if int(config.iters_per_dispatch) > 1:
            # The reduced-metrics burst cadence is RETIRED for sweeps:
            # fused_chunk subsumes it (same scan fusion, but metrics come
            # back stacked per iteration and checkpoints go async) and
            # measured >= it at every chunk size. Reject loudly rather
            # than silently training at cadence 1.
            raise SystemExit(
                "iters_per_dispatch is retired for population sweeps — "
                "set fused_chunk=K instead (the Anakin mode: K vmapped "
                "iterations per lax.scan dispatch, per-member metrics "
                "stacked per iteration, async population checkpoints)"
            )
        self._multihost = jax.process_count() > 1
        if self._fused_chunk and self._multihost:
            raise SystemExit(
                "fused-scan sweeps are single-host for now (the async "
                "population checkpoint writer allgathers off-thread, "
                "which has no cross-host durability barrier); drop "
                "fused_chunk or run single-process"
            )
        if self._multihost:
            # Multi-host sweeps: every process initializes ONLY its own
            # members (per-host construction, parallel/distributed.py
            # style), the seed axis is globally 'dp'-sharded, and
            # checkpoint IO allgathers to the coordinator. Requires a
            # mesh spanning every global device.
            assert mesh is not None, (
                "multi-host sweeps need a global mesh (cfg mesh={dp: -1})"
            )
            assert num_seeds % jax.process_count() == 0, (
                f"num_seeds={num_seeds} must be divisible by "
                f"process_count={jax.process_count()} (even per-host "
                "member construction)"
            )
        # Every member runs the same per-member budget, so the single-run
        # horizon formula applies unchanged (bit-compat with Trainer).
        ppo = fill_ent_schedule(ppo, env_params, config)
        self.env_params = env_params
        # Env-generic dispatch (envs/): formation params resolve to the
        # legacy env/formation.py functions verbatim, so member i stays
        # bit-identical to Trainer(seed=config.seed + i) on the default env.
        self.env_spec = spec_for_params(env_params)
        self.ppo = ppo
        self.config = config
        self.num_seeds = num_seeds
        self.model = model or MLPActorCritic(
            act_dim=env_params.act_dim, log_std_init=ppo.log_std_init
        )
        self.per_formation = getattr(self.model, "per_formation", False)
        m = config.num_formations

        if self.per_formation:
            dummy_obs = jnp.zeros(
                (1, env_params.num_agents, env_params.obs_dim), jnp.float32
            )
        else:
            dummy_obs = jnp.zeros((1, env_params.obs_dim), jnp.float32)

        model_ref = self.model  # close over the module, not self
        env_spec = self.env_spec  # likewise — init_member is jit/vmapped

        self._lr_sweep = learning_rates is not None
        if self._lr_sweep:
            # float() each element: YAML 1.1 keeps dotless sci-notation
            # ("3e-4") as STRINGS, so the documented CLI syntax
            # learning_rates=[3e-4,1e-3] arrives as a list of str.
            lrs = jnp.asarray(
                [float(x) for x in np.ravel(learning_rates)], jnp.float32
            )
            assert lrs.shape == (num_seeds,), (
                f"learning_rates must have one entry per member: got "
                f"{lrs.shape[0]} for num_seeds={num_seeds}"
            )
            # One SHARED transform whose rate is optimizer-STATE, so the
            # vmap can batch it per member (a per-member closure would
            # need per-member tx callables, which TrainState can't carry).
            tx = ppo.make_optimizer(inject_lr=True)
        else:
            lrs = None
            tx = ppo.make_optimizer()

        def init_member(seed: Array, lr: Optional[Array] = None):
            # EXACTLY Trainer.__init__'s key discipline so member i ==
            # Trainer(seed=config.seed + i) bit-for-bit.
            key = jax.random.PRNGKey(seed)
            key, k_init, k_env = jax.random.split(key, 3)
            params = model_ref.init(k_init, dummy_obs)
            train_state = TrainState.create(
                apply_fn=model_ref.apply, params=params, tx=tx
            )
            if lr is not None:
                # inject_hyperparams keeps the rate in its state's
                # hyperparams dict; overwrite it with this member's value.
                clip_s, inject_s = train_state.opt_state
                assert hasattr(inject_s, "hyperparams"), (
                    "expected InjectHyperparamsState second in the chain"
                )
                inject_s = inject_s._replace(
                    hyperparams={
                        **inject_s.hyperparams, "learning_rate": lr
                    }
                )
                train_state = train_state.replace(
                    opt_state=(clip_s, inject_s)
                )
            env_state = env_spec.reset_batch(k_env, env_params, m)
            obs = env_spec.obs(env_state, env_params)
            return train_state, env_state, obs, key

        self._mesh = mesh
        if mesh is not None:
            # Validate the mesh BEFORE the population init: compiling the
            # vmapped init just to then fail an assert wastes ~10s.
            assert set(mesh.axis_names) == {"dp"}, (
                f"sweep meshes shard the SEED axis over 'dp' only; got "
                f"axes {tuple(mesh.axis_names)} — an 'sp' axis would "
                "replicate every member redundantly across it"
            )
            dp = int(mesh.shape["dp"])
            assert num_seeds % dp == 0, (
                f"num_seeds={num_seeds} must be divisible by the mesh dp "
                f"axis ({dp}) so every device holds the same member count"
            )

        seeds = config.seed + jnp.arange(num_seeds)
        init_args = (seeds,) if lrs is None else (seeds, lrs)
        if self._multihost:
            # Per-host construction: this process initializes ONLY its own
            # contiguous member block and the population is assembled as
            # globally 'dp'-sharded arrays (mirrors
            # parallel.reset_batch_sharded — required for correctness:
            # cross-process device_put of host-global arrays is
            # impossible). Checkpoint IO does transiently allgather the
            # population to every host (see _to_host).
            from marl_distributedformation_tpu.parallel import (
                global_from_local,
            )

            start, count = self._member_slice()
            local = jax.jit(jax.vmap(init_member))(
                *(a[start : start + count] for a in init_args)
            )
            (
                self.train_state,
                self.env_state,
                self.obs,
                self.key,
            ) = global_from_local(jax.device_get(local), mesh)
        else:
            (
                self.train_state,
                self.env_state,
                self.obs,
                self.key,
            ) = jax.jit(jax.vmap(init_member))(*init_args)
        self.learning_rates = lrs
        # Host copy for checkpoint/summary provenance — reading the device
        # array per member would pay a round trip each (tunneled TPU).
        self._lrs_host = None if lrs is None else np.asarray(lrs)
        self.num_timesteps = 0  # per-member agent-transitions (SB3 unit)
        self.log_dir = config.log_dir or str(
            repo_root() / "logs" / config.name
        )
        if config.resume:
            # Restore BEFORE mesh placement so the resumed population is
            # re-placed on the dp sharding exactly like a fresh one.
            self._try_resume()

        if mesh is not None and not self._multihost:
            # Multi-host state is already globally placed by
            # global_from_local (cross-host device_put is impossible).
            from jax.sharding import NamedSharding, PartitionSpec

            shard = NamedSharding(mesh, PartitionSpec("dp"))
            place = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda x: jax.device_put(x, shard), t
            )
            self.train_state = place(self.train_state)
            self.env_state = place(self.env_state)
            self.obs = place(self.obs)
            self.key = place(self.key)

        iteration = make_ppo_iteration(
            env_params, ppo, self.per_formation, None
        )
        # In-program health word + skip-update guard (train/recovery.py):
        # wrapped BEFORE the vmap, so every member carries its OWN flags
        # and a diverged member skips its own updates while the rest of
        # the population trains on. Flags stack into the chunk metrics
        # like any other entry; the drain seam counts the skips.
        from marl_distributedformation_tpu.train.recovery import wrap_health

        iteration = wrap_health(iteration, config)
        iteration_pop = jax.vmap(iteration)
        if mesh is not None:
            # shard_map over the seed axis, not bare jit-under-mesh: each
            # device runs its K/D members entirely locally, so per-device
            # code (the Pallas knn kernels, which the SPMD partitioner
            # cannot split — see parallel.make_dp_step) keeps working, and
            # XLA provably inserts zero collectives. One partition spec
            # broadcasts over every pytree leaf (all carry the leading
            # seed axis).
            from jax.sharding import PartitionSpec

            spec = PartitionSpec("dp")
            iteration_pop = shard_map(
                iteration_pop,
                mesh=mesh,
                in_specs=spec,
                out_specs=spec,
                # Collective-free program: the varying-across-mesh checker
                # buys nothing and trips on pallas outputs (see
                # parallel/mesh.py).
                check_vma=False,
            )
        if self._fused_chunk:
            # Anakin population mode: fused_chunk whole vmapped
            # iterations in ONE lax.scan — the (members,) axis rides
            # through the scan untouched, so per-member per-iteration
            # metrics come back stacked (fused_chunk, members, ...).
            iteration_pop = make_fused_chunk(iteration_pop, self._fused_chunk)
        # Compile-once receipt for the population program (bench records
        # it; guard_retraces=1 enforces it).
        self.retrace_guard = profiling.RetraceGuard(
            "sweep_iteration", max_traces=config.guard_retraces or None
        )
        self._iteration = profiling.ledgered_jit(
            iteration_pop,
            self.retrace_guard,
            subsystem="sweep",
            program="sweep_iteration",
            donate_argnums=(0, 1),
        )
        self._vec_steps_since_save = 0
        self.num_envs = m * env_params.num_agents

    # ------------------------------------------------------------------

    def _member_slice(self):
        """``(start, count)`` of this process's contiguous member block —
        the seed-axis analog of ``parallel.local_formation_slice``."""
        n_proc = jax.process_count()
        count = self.num_seeds // n_proc
        return jax.process_index() * count, count

    def _to_host(self, tree):
        """Full host copy of a (possibly cross-host-sharded) tree: plain
        ``device_get`` single-controller, allgather multi-host (the
        coordinator needs every member for checkpoints/summaries;
        multihost_utils has no coordinator-only gather, so every host
        transiently holds the full population — fine at this env's state
        sizes: K members x M formations of 2-D agent positions is MBs,
        not the multi-GB regime where a p2p path would be warranted)."""
        if not self._multihost:
            return jax.device_get(tree)
        from jax.experimental import multihost_utils

        return jax.tree_util.tree_map(
            np.asarray, multihost_utils.process_allgather(tree, tiled=True)
        )

    @property
    def total_timesteps(self) -> int:
        return default_total_timesteps(self.config)

    def _dispatch(self, rollouts: int):
        """Dispatch the jitted population program once (``rollouts``
        iterations for every member) and advance the host counters."""
        (
            self.train_state,
            self.env_state,
            self.obs,
            self.key,
            metrics,
        ) = self._iteration(
            self.train_state, self.env_state, self.obs, self.key
        )
        self.num_timesteps += rollouts * self.ppo.n_steps * self.num_envs
        self._vec_steps_since_save += rollouts * self.ppo.n_steps
        return metrics

    def run_iteration(self) -> Dict[str, Array]:
        """One vectorized iteration; metrics values carry a leading (K,)
        seed axis."""
        assert not self._fused_chunk, (
            "fused_chunk sweeps dispatch via run_chunk() (stacked "
            "per-iteration metrics), not run_iteration()"
        )
        return self._dispatch(1)

    def run_chunk(self) -> Dict[str, Array]:
        """Anakin population mode: dispatch ONE fused-scan chunk
        (``fused_chunk`` vmapped iterations) and return the metrics stack
        as DEVICE arrays with leading ``(fused_chunk, num_seeds)`` axes.
        Returns as soon as the program is enqueued — ``_train_fused``
        overlaps the previous chunk's drain with this one's execution."""
        assert self._fused_chunk > 0, (
            "run_chunk() needs fused_chunk > 0 (Anakin mode)"
        )
        return self._dispatch(self._fused_chunk)

    def _host_population(self) -> Dict[str, Any]:
        """ONE batched device pull of everything checkpoints need — on a
        tunneled TPU, per-leaf-per-member transfers would pay K x leaves
        round trips (the trainer-wide rule: sync once, slice on host).
        Both the per-member checkpoints and the population sweep_state
        file are built from this single pull."""
        return self._to_host(
            {
                "params": self.train_state.params,
                "opt_state": self.train_state.opt_state,
                "key": self.key,
                "env_state": self.env_state,
                "obs": self.obs,
            }
        )

    def member_state(
        self,
        i: int,
        host: Optional[Dict[str, Any]] = None,
        steps: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Slice member ``i``'s full learner state out of the population —
        a standard (Trainer-compatible) checkpoint target. Pass ``host``
        (from ``_host_population``) when saving many members so the
        device pull happens once; ``steps`` pins the recorded progress
        (the async writer captures it at submit time — the live counter
        has moved on by the time the writer thread runs)."""
        if host is None:
            host = self._host_population()
        # np.array (not asarray): slices of the shared host pull must be
        # OWNING copies, or every member's checkpoint dict aliases (and
        # keeps alive) the full K-member tree.
        take = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: np.array(x[i]), t
        )
        state = {
            "policy": self.model.__class__.__name__,
            "params": take(host["params"]),
            "key": np.array(host["key"][i]),
            "num_timesteps": (
                self.num_timesteps if steps is None else int(steps)
            ),
            # Provenance the single-run resume path checks: fine-tuning a
            # member at a different rate than it trained with warns loudly.
            "learning_rate": float(
                self._lrs_host[i]
                if self._lrs_host is not None
                else self.ppo.learning_rate
            ),
        }
        if not self._lr_sweep:
            # lr-sweep members use the inject_hyperparams state tree, which
            # the single-run optimizer can't restore into — omit it from
            # MEMBER checkpoints (the tolerant resume path re-estimates
            # Adam moments, same as SB3-imported checkpoints). The
            # population sweep_state file keeps the full tree either way.
            state["opt_state"] = take(host["opt_state"])
        return state

    def save(self) -> None:
        """Per-member checkpoints under ``{log_dir}/seed{i}/`` — each one
        plays back / resumes through the standard single-run tooling
        (``visualize_policy.py name={name}/seed{i}``) — plus ONE
        population-state file (``sweep_state_{steps}_steps.msgpack``)
        carrying the full batched learner + env state, so an interrupted
        sweep resumes exactly (``resume=true``) instead of restarting."""
        from marl_distributedformation_tpu.parallel import is_coordinator

        host = self._host_population()
        on_coord = is_coordinator()
        for i in range(self.num_seeds):
            # Non-coordinators skip both the member-state slicing (K
            # owning copies nobody would write) and the per-file barrier;
            # the single synced sweep_state write below is the durability
            # point for the whole logical checkpoint.
            save_checkpoint(
                Path(self.log_dir) / f"seed{i}",
                self.num_timesteps,
                self.member_state(i, host) if on_coord else None,
                sync=False,
            )
        save_sweep_state(
            self.log_dir, self.num_timesteps, self._population_target(host)
        )
        self._vec_steps_since_save = 0

    def _population_target(
        self, host: Dict[str, Any], steps: Optional[int] = None
    ) -> Dict[str, Any]:
        """The full resume anchor: everything ``run_iteration`` threads,
        batched over the (K,) seed axis — including the lr-sweep's
        ``inject_hyperparams`` state, which member checkpoints must omit
        (their tree differs from the single-run optimizer's) — plus the
        identity fields resume validates against. Built from the
        ``_host_population`` pull so a save costs ONE device round trip."""
        target: Dict[str, Any] = {
            "policy": self.model.__class__.__name__,
            "num_seeds": self.num_seeds,
            "seed": int(self.config.seed),
            "num_formations": int(self.config.num_formations),
            "num_timesteps": (
                self.num_timesteps if steps is None else int(steps)
            ),
            **host,
        }
        if self._lrs_host is not None:
            target["learning_rates"] = self._lrs_host
        return target

    def _write_population_files(self, tree: Dict[str, Any], steps: int):
        """Write one LOGICAL population checkpoint — every member's
        ``rl_model_{steps}_steps`` file plus the ``sweep_state`` resume
        anchor — from ``tree`` (a host pull, or a ``device_snapshot`` when
        called on the async writer thread; ``device_get`` drains either in
        one batched transfer). Single-controller only: the async path
        fail-fasts multi-host in ``__init__``, so no durability barrier
        is needed here. The sweep_state anchor is written LAST — if the
        process dies mid-logical-checkpoint, resume discovery never sees
        an anchor whose member files are missing."""
        host = jax.device_get(tree)
        for i in range(self.num_seeds):
            _write_atomic(
                checkpoint_path(Path(self.log_dir) / f"seed{i}", steps),
                self.member_state(i, host, steps),
            )
        _write_atomic(
            sweep_state_path(self.log_dir, steps),
            self._population_target(host, steps),
        )

    def save_async(self, writer: AsyncCheckpointWriter) -> None:
        """Chunk-boundary population checkpoint that never stalls the
        dispatch lane: snapshot the full sweep state ON DEVICE
        (``utils.device_snapshot`` — the copies are enqueued behind the
        chunk that produced the state, so the next chunk's donation
        cannot invalidate them), then hand the snapshot to the writer
        thread, which drains and writes every member file + the
        sweep_state anchor while the device keeps training. Chunk
        boundary == checkpoint boundary == bit-exact resume boundary."""
        assert not self._multihost
        snapshot = device_snapshot(
            {
                "params": self.train_state.params,
                "opt_state": self.train_state.opt_state,
                "key": self.key,
                "env_state": self.env_state,
                "obs": self.obs,
            }
        )
        writer.submit_write(
            functools.partial(
                self._write_population_files, snapshot, self.num_timesteps
            )
        )
        self._vec_steps_since_save = 0

    def _try_resume(self) -> None:
        """Restore the latest ``sweep_state_*`` population checkpoint into
        the freshly-initialized state. The restored run continues
        bit-identically to an uninterrupted one (pinned by
        tests/test_sweep.py): params, the batched optimizer state
        (moments + per-member injected rates), member PRNG streams, env
        state, and the step counter all come from the file."""
        if self._multihost:
            self._try_resume_multihost()
            return
        path = latest_sweep_state(self.log_dir)
        if path is None:
            self._note_no_population_file()
            return
        restored, steps, stored_lrs = self._read_population_file(path)
        # Owning copies BEFORE the donating dispatch sees this state:
        # msgpack_restore leaves can view the checkpoint's byte buffer,
        # and donating an aliased buffer is a use-after-free on the
        # zero-copy CPU backend (utils.own_restored).
        restored = own_restored(restored)
        self._adopt_checkpoint_lrs(stored_lrs)
        self.train_state = self.train_state.replace(
            params=restored["params"], opt_state=restored["opt_state"]
        )
        self.key = jnp.asarray(restored["key"])
        self.env_state = restored["env_state"]
        self.obs = jnp.asarray(restored["obs"])
        self.num_timesteps = steps
        print(
            f"[sweep] resumed {self.num_seeds}-member population from "
            f"{path} at {self.num_timesteps} steps"
        )

    def _note_no_population_file(self) -> None:
        if latest_checkpoint(Path(self.log_dir) / "seed0") is not None:
            print(
                "[sweep] resume=true but no sweep_state_* population "
                f"checkpoint under {self.log_dir} (member checkpoints "
                "predate sweep resume or were written by an old "
                "version); starting fresh — resume individual members "
                "via their seed{i}/ dirs instead"
            )

    def _host_template(self) -> Dict[str, Any]:
        """Host-zero template with the GLOBAL population shapes — usable
        on every process even when the live state is cross-host-sharded
        (shape/dtype are known without addressability)."""
        template = {
            "params": self.train_state.params,
            "opt_state": self.train_state.opt_state,
            "key": self.key,
            "env_state": self.env_state,
            "obs": self.obs,
        }
        return jax.tree_util.tree_map(
            lambda x: np.zeros(x.shape, x.dtype), template
        )

    def _read_population_file(self, path):
        """Parse + validate a sweep_state file; returns
        ``(restored_host_tree, num_timesteps, stored_lrs)``. Raises
        SystemExit on any identity/compatibility mismatch."""
        from flax import serialization

        from marl_distributedformation_tpu.utils.checkpoint import (
            msgpack_restore_file,
        )

        raw = msgpack_restore_file(path)
        ident = {
            "policy": self.model.__class__.__name__,
            "num_seeds": self.num_seeds,
            "seed": int(self.config.seed),
            # num_formations drifting silently would corrupt the timestep
            # accounting (num_envs uses the NEW config while the restored
            # env batch keeps the OLD M — batch dims are data-driven, so
            # nothing else would catch it).
            "num_formations": int(self.config.num_formations),
        }
        for field, want in ident.items():
            got = raw.get(field)
            if got != want and str(got) != str(want):
                raise SystemExit(
                    f"sweep resume mismatch: checkpoint {path} was written "
                    f"with {field}={got!r} but this run uses {want!r} — "
                    "member identities would silently change"
                )
        stored_lrs = raw.get("learning_rates")
        if (stored_lrs is None) != (self._lrs_host is None):
            raise SystemExit(
                f"sweep resume mismatch: checkpoint {path} was written "
                f"{'with' if stored_lrs is not None else 'without'} "
                "learning_rates but this run is the opposite — the "
                "optimizer state trees are incompatible; pass the same "
                "learning_rates the sweep was started with"
            )
        if stored_lrs is not None:
            stored_lrs = np.asarray(stored_lrs, np.float32)
        template = self._host_template()
        for name in (*template, "num_timesteps"):
            if name not in raw:
                raise SystemExit(
                    f"sweep resume: checkpoint {path} is missing {name!r} "
                    "— truncated or foreign file"
                )
        restored = {
            name: serialization.from_state_dict(tmpl, raw[name])
            for name, tmpl in template.items()
        }
        return restored, int(raw["num_timesteps"]), stored_lrs

    def _adopt_checkpoint_lrs(self, stored_lrs) -> None:
        if stored_lrs is None:
            return
        if not np.allclose(stored_lrs, self._lrs_host, rtol=1e-6):
            print(
                "[sweep] WARNING: checkpoint member learning rates "
                f"{stored_lrs.tolist()} differ from this run's "
                f"{self._lrs_host.tolist()} — continuing at the "
                "CHECKPOINT's rates (they live in the restored "
                "optimizer state)"
            )
        # Keep provenance truthful: member checkpoints record the rate
        # actually used, which is the restored one.
        self._lrs_host = stored_lrs
        self.learning_rates = jnp.asarray(stored_lrs)

    def _try_resume_multihost(self) -> None:
        """Multi-host population resume: the coordinator reads + validates
        the file, every host receives the identical host state, slices its
        own member block, and re-places it globally — mirroring
        ``utils.broadcast_restore``'s fail-fast protocol (on a coordinator
        error peers are released with found=0 BEFORE the error re-raises,
        so nobody blocks inside the broadcast)."""
        from jax.experimental import multihost_utils

        from marl_distributedformation_tpu.parallel import (
            global_from_local,
            is_coordinator,
        )

        template = self._host_template()
        restored, steps, found, err = template, 0, 0, None
        stored_lrs = (
            np.zeros_like(self._lrs_host)
            if self._lrs_host is not None else None
        )
        if is_coordinator():
            try:
                path = latest_sweep_state(self.log_dir)
                if path is None:
                    self._note_no_population_file()
                else:
                    restored, steps, stored_lrs = (
                        self._read_population_file(path)
                    )
                    found = 1
            except BaseException as e:  # noqa: BLE001 — incl. SystemExit;
                # converted to fail-fast after releasing the peers
                restored, err = template, e
        found = int(multihost_utils.broadcast_one_to_all(np.int32(found)))
        if err is not None:
            raise err
        if not found:
            return
        payload = [restored, np.int64(steps)]
        if stored_lrs is not None:
            payload.append(np.asarray(stored_lrs, np.float32))
        payload = multihost_utils.broadcast_one_to_all(payload)
        restored, steps = payload[0], int(payload[1])
        if stored_lrs is not None:
            self._adopt_checkpoint_lrs(np.asarray(payload[2]))
        start, count = self._member_slice()
        local = jax.tree_util.tree_map(
            lambda x: x[start : start + count], restored
        )
        placed = global_from_local(local, self._mesh)
        self.train_state = self.train_state.replace(
            params=placed["params"], opt_state=placed["opt_state"]
        )
        self.key = placed["key"]
        self.env_state = placed["env_state"]
        self.obs = placed["obs"]
        self.num_timesteps = steps
        print(
            f"[sweep] process {jax.process_index()} resumed "
            f"{self.num_seeds}-member population (broadcast) at "
            f"{self.num_timesteps} steps"
        )

    def train(self) -> Dict[str, float]:
        """Full sweep; logs population-aggregate metrics per rollout and
        writes per-member checkpoints + a ranking summary at the end.
        Returns the final aggregate record."""
        if self._fused_chunk:
            return self._train_fused()
        logger = MetricsLogger(
            self.log_dir,
            run_name=self.config.name,
            use_wandb=self.config.use_wandb,
            use_tensorboard=self.config.use_tensorboard,
        )
        meter = Throughput()
        tracer = profiling.TraceWindow(
            self.log_dir, self.config.profile, self.config.profile_iterations
        )
        record: Dict[str, float] = {}
        iteration = 0
        metrics = None
        try:
            while self.num_timesteps < self.total_timesteps:
                tracer.before_dispatch()
                metrics = self.run_iteration()
                tracer.after_dispatch(metrics)
                iteration += 1
                meter.tick(
                    self.ppo.n_steps
                    * self.config.num_formations
                    * self.num_seeds
                )
                if iteration % self.config.log_interval == 0:
                    host = self._to_host(metrics)  # one batched pull
                    record_health_flags(host)  # drain-seam skip counter
                    record = self._aggregate(host)
                    record["env_steps_per_sec"] = meter.rate()
                    logger.log(record, self.num_timesteps)
                if (
                    self.config.checkpoint
                    and self._vec_steps_since_save >= self.config.save_freq
                ):
                    self.save()
            if metrics is not None:
                # Rank on the FINAL iteration's rewards even when
                # log_interval didn't land on it — a stale ranking would
                # disagree with the final checkpoints it points at.
                final = self._to_host(metrics)
                record = self._aggregate(final)
                record["env_steps_per_sec"] = meter.rate()
                if self.config.checkpoint:
                    self.save()
                    self._write_summary(np.asarray(final["reward"]))
        finally:
            tracer.close()
            logger.close()
        return record

    # ------------------------------------------------------------------
    # Anakin population mode (fused_chunk > 0): whole-loop scan dispatch
    # for every member at once, double-buffered telemetry drain, async
    # population checkpoints (docs/training.md "Population fusion").
    # ------------------------------------------------------------------

    def _train_fused(self) -> Dict[str, float]:
        """Fused-scan population driver: dispatch chunk N+1 BEFORE
        draining chunk N's stacked ``(fused_chunk, num_seeds, ...)``
        telemetry (the device trains while the host aggregates and logs),
        and checkpoint the whole population at chunk boundaries on the
        background writer off a device-side snapshot. Emitted records are
        per-iteration population aggregates — identical cadence and step
        stamps to the host loop's."""
        logger = MetricsLogger(
            self.log_dir,
            run_name=self.config.name,
            use_wandb=self.config.use_wandb,
            use_tensorboard=self.config.use_tensorboard,
        )
        meter = Throughput()
        writer = AsyncCheckpointWriter() if self.config.checkpoint else None
        tracer = profiling.TraceWindow(
            self.log_dir, self.config.profile, self.config.profile_iterations
        )
        record: Dict[str, float] = {}
        final_rewards = None
        k = self._fused_chunk
        iteration = 0
        pending = None  # the chunk in flight, drained one dispatch later
        try:
            while self.num_timesteps < self.total_timesteps:
                steps_before = self.num_timesteps
                tracer.before_dispatch()
                stacked = self.run_chunk()
                tracer.after_dispatch(stacked)
                if pending is not None:
                    rec, final_rewards = self._drain_chunk(
                        logger, meter, *pending
                    )
                    record = rec or record
                pending = (stacked, iteration, steps_before)
                iteration += k
                if (
                    writer is not None
                    and self._vec_steps_since_save >= self.config.save_freq
                ):
                    self.save_async(writer)
            if pending is not None:
                rec, final_rewards = self._drain_chunk(
                    logger, meter, *pending
                )
                record = rec or record
            if self.config.checkpoint:
                if writer is not None:
                    self.save_async(writer)
                    writer.close()  # final write durable before the summary
                    writer = None
                if final_rewards is not None:
                    # Rank on the final iteration's rewards, matching the
                    # final checkpoints (the host-loop rule).
                    self._write_summary(final_rewards)
        finally:
            tracer.close()
            if writer is not None:
                # Unwinding on an error: drain the writer without letting
                # a secondary write failure mask the original exception.
                writer.close_quietly()
            logger.close()
        return record

    def _drain_chunk(self, logger, meter, stacked, first_iteration,
                     steps_before):
        """ONE batched ``device_get`` for a whole chunk's population
        telemetry, then emit per-iteration aggregate records exactly like
        the host loop would (``log_interval`` phased on the global
        iteration index). Called after the NEXT chunk has been
        dispatched, so this blocks on the finished chunk while the device
        already runs the new one. Returns ``(last_emitted_record,
        final_iteration_rewards)`` — the rewards feed the ranking
        summary."""
        host = jax.device_get(stacked)
        profiling.sample_device_watermark()  # drain boundary (ledger)
        # Drain-seam health pin (train/recovery.py): per-member skips
        # land in train_skipped_updates_total — the flags arrived in
        # the same batched device_get as the rest of the telemetry.
        record_health_flags(host)
        meter.tick(
            self._fused_chunk
            * self.ppo.n_steps
            * self.config.num_formations
            * self.num_seeds
        )
        per_iter = self.ppo.n_steps * self.num_envs
        record: Dict[str, float] = {}
        for i in range(self._fused_chunk):
            if (first_iteration + i + 1) % self.config.log_interval:
                continue
            rec = self._aggregate(
                {name: v[i] for name, v in host.items()}
            )
            rec["env_steps_per_sec"] = meter.rate()
            logger.log(rec, steps_before + (i + 1) * per_iter)
            record = rec
        return record, np.asarray(host["reward"][-1])

    def _aggregate(self, host: Dict[str, np.ndarray]) -> Dict[str, float]:
        return population_aggregate(host, self.config.seed)

    def _write_summary(self, rewards: Optional[np.ndarray]) -> None:
        from marl_distributedformation_tpu.parallel import is_coordinator

        if rewards is None or not is_coordinator():
            return
        extra = None
        if self._lrs_host is not None:
            extra = {
                "learning_rates": [float(lr) for lr in self._lrs_host]
            }
        write_sweep_summary(
            self.log_dir, self.config.seed, self.num_seeds, rewards, extra
        )


def population_aggregate(
    host: Dict[str, np.ndarray], seed0: int
) -> Dict[str, float]:
    """Population means under the CANONICAL metric names (the reference
    metric-name contract, utils/logging.py — so JSONL consumers and the
    stdout brief keep working), plus population spread fields. The
    single sweep metric contract — shared by ``SweepTrainer`` and
    ``HeteroSweepTrainer`` so the two cannot drift."""
    rewards = np.asarray(host["reward"])
    record = {k: float(np.mean(v)) for k, v in host.items()}
    record["reward_best"] = float(rewards.max())
    record["reward_worst"] = float(rewards.min())
    record["best_seed"] = int(seed0 + rewards.argmax())
    return record


def write_sweep_summary(
    log_dir,
    seed0: int,
    num_seeds: int,
    rewards: np.ndarray,
    extra: Optional[Dict[str, Any]] = None,
) -> None:
    """The ``sweep_summary.json`` artifact contract (consumed by
    evaluate.py's member ranking and visualize_policy.py's best-member
    descent) — shared by both population trainers."""
    summary = {
        "seeds": [int(seed0 + i) for i in range(num_seeds)],
        "final_reward": [float(r) for r in rewards],
        "best_seed": int(seed0 + rewards.argmax()),
        "best_dir": f"seed{int(rewards.argmax())}",
    }
    if extra:
        summary.update(extra)
    path = Path(log_dir) / "sweep_summary.json"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(summary, indent=2))
