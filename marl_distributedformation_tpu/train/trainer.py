"""End-to-end PPO trainer: one jitted iteration = rollout + GAE + update.

Replaces the reference's training driver (``run()``, vectorized_env.py:112-137)
and the SB3 ``learn`` loop it delegates to (SURVEY.md §3.1). The entire hot
path — policy forward, action sampling, vectorized env stepping, GAE, and all
minibatch epochs — is a single XLA program per iteration; the host loop only
dispatches iterations, emits per-rollout metrics, and writes checkpoints.

Timestep accounting matches SB3: ``num_timesteps`` counts agent-transitions
(``+= num_envs = M*N`` per vec-step, SURVEY.md §2.2), and the default budget
is ``5000 * num_formations`` (vectorized_env.py:116,134).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import threading
import time
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax.training.train_state import TrainState

from marl_distributedformation_tpu.algo import (
    MinibatchData,
    PPOConfig,
    collect_rollout,
    compute_gae,
    ppo_update,
)
from marl_distributedformation_tpu.chaos.plane import (
    InjectedFault,
    fault_point,
)
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.formation import compute_obs
from marl_distributedformation_tpu.envs import spec_for_params
from marl_distributedformation_tpu.models import MLPActorCritic
from marl_distributedformation_tpu.obs.metrics import get_registry
from marl_distributedformation_tpu.utils import profiling
from marl_distributedformation_tpu.utils import (
    AsyncCheckpointWriter,
    MetricsLogger,
    Throughput,
    checkpoint_path,
    device_snapshot,
    own_restored,
    repo_root,
    restore_latest_partial,
    save_checkpoint,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Run-level configuration (what the reference spreads across cfg,
    ``run()``, and SB3 constructor arguments)."""

    num_formations: int = 1000  # cfg/config.yaml:3
    total_timesteps: Optional[int] = None  # default 5000 * M agent-transitions
    seed: int = 0
    save_freq: int = 10  # vec-steps between checkpoints (vectorized_env.py:124)
    checkpoint: bool = True
    name: str = "default"
    log_dir: Optional[str] = None  # default <repo>/logs/{name}
    use_wandb: bool = False
    use_tensorboard: bool = False  # SB3 writes tensorboard_log scalars
    #   (reference vectorized_env.py:129); opt-in equivalent via torch's
    #   SummaryWriter into {log_dir}/tensorboard/
    resume: bool = False
    log_interval: int = 1  # emit metrics every k rollouts
    iters_per_dispatch: int = 1  # rollout+update iterations fused into ONE
    #   jitted program via lax.scan — one host dispatch (one tunnel RTT)
    #   advances R iterations. Metrics/logging/checkpoint cadence quantize
    #   to R; metrics are the mean over the burst (dones: sum).
    fused_chunk: int = 0  # Anakin mode (docs/training.md): >0 compiles K
    #   rollout+update iterations into ONE lax.scan program with the full
    #   training state as the donated carry. Per-iteration metrics come
    #   back STACKED (one batched device_get per chunk, double-buffered
    #   against the next chunk's execution) and checkpoints are written by
    #   a background thread off a device-side snapshot. Chunk boundary =
    #   checkpoint boundary; logging stays per-iteration. Mutually
    #   exclusive with iters_per_dispatch (the host-loop burst spelling).
    profile: bool = False  # capture a jax.profiler trace of a few
    #   post-warmup dispatches into {log_dir}/profile/ (profile=true CLI).
    #   Composes with fused_chunk: the capture window is DISPATCH-grained
    #   (utils.profiling.TraceWindow), so fused mode traces
    #   profile_iterations whole chunks instead of fail-fasting.
    profile_iterations: int = 3  # dispatches to trace (chunks when fused)
    # Runtime tracing guards (analysis/guards.py; docs/static_analysis.md).
    guard_retraces: int = 0  # >0: fail the run if the jitted train
    #   iteration compiles more than this many times (1 = the steady-state
    #   contract: identical shapes must never retrace). 0 = count only.
    guard_transfers: bool = False  # disallow device->host transfers during
    #   post-warmup dispatches (the compile dispatch is exempt — constant
    #   uploads during tracing are legitimate)
    guard_nans: bool = False  # jax_debug_nans around every dispatch: ops
    #   producing NaN re-run op-by-op and raise at the source op
    # Self-healing train lane (train/recovery.py, docs/recovery.md).
    health: bool = False  # in-program health word + skip-update guard:
    #   every iteration computes finite-loss / bounded-grad-norm /
    #   param-drift flags and carries the PREVIOUS state through when
    #   flagged (identity update). Flags ride the stacked chunk metrics
    #   (zero extra dispatches); healthy-run outputs are bitwise
    #   identical health on vs off, and budget-1 receipts hold.
    health_grad_norm_max: float = 1.0e6  # raw global-grad-norm bound
    #   (healthy pre-clip norms reach the hundreds; divergence is
    #   1e18+/NaN — see train/recovery.py)
    health_param_drift_max: float = 10.0  # |p_new| <= this * (|p_old|+1)
    recovery: bool = False  # host-side escalation ladder at the drain
    #   seam (requires health=true): sustained breach -> rollback to the
    #   last-good checkpoint with a folded-in recovery counter advancing
    #   the PRNG stream -> bounded retries -> halt with flight record.
    #   Transitions land in logs/{name}/recovery.jsonl + train_* gauges.
    recovery_breach_iters: int = 3  # consecutive skipped iterations
    #   that count as a sustained breach
    recovery_max_rollbacks: int = 3  # retry budget before halting
    recovery_lr_backoff: float = 1.0  # per-rollback learning-rate
    #   multiplier (!= 1.0 builds the optimizer with inject_hyperparams
    #   so the rate lives in opt state — note that changes the opt-state
    #   layout vs default checkpoints)
    recovery_severity_backoff: float = 1.0  # per-rollback scenario
    #   severity multiplier (pure schedule data — no recompile)
    keep_last_n: int = 0  # checkpoint retention ring: keep only the
    #   newest N rl_model_* checkpoints (0 = unbounded, the legacy
    #   behavior). Quarantine-aware and never prunes the recovery
    #   ladder's current last-good rollback target.
    # Sebulba lane (train/sebulba/, docs/sebulba.md): the split
    # acting/learning architecture next to Anakin.
    architecture: str = "anakin"  # "anakin" (fused same-device dispatch,
    #   every mode above) | "sebulba" (actor slice + learner slice joined
    #   by a bounded host-side TransferQueue and a latest-wins ParamBus;
    #   fused_chunk is reinterpreted as K, the batches the learner drains
    #   per fused update chunk)
    actor_devices: int = 1  # sebulba: local devices assigned to the
    #   actor slice (the remainder learn; at least one device is always
    #   kept for the learner — a single-device host time-shares)
    transfer_queue_depth: int = 2  # sebulba: bound on in-flight
    #   trajectory batches; a full queue blocks the actor (backpressure),
    #   so the actor can never run more than this many rollouts ahead
    max_param_staleness: int = 2  # sebulba: drop (never train on) a
    #   batch acted with params more than this many learner updates old


def default_total_timesteps(config: "TrainConfig") -> int:
    """SB3 budget semantics shared by every trainer shell: explicit
    ``total_timesteps``, else ``5000 * M`` agent-transitions
    (reference vectorized_env.py:116,134)."""
    if config.total_timesteps is not None:
        return config.total_timesteps
    return 5000 * config.num_formations


def fill_ent_schedule(
    ppo: PPOConfig,
    env_params: EnvParams,
    config: "TrainConfig",
    iterations: Optional[int] = None,
) -> PPOConfig:
    """Fill ``ppo.total_iterations`` (the shared decay horizon for the
    ``ent_coef_final`` entropy schedule and the ``log_std_final``
    noise-decay schedule) from the run's planned iteration count.
    No-op when no schedule is requested or the horizon is already set —
    in particular, the default config path is left bit-identical."""
    if (
        ppo.ent_coef_final is None and ppo.log_std_final is None
    ) or ppo.total_iterations > 0:
        return ppo
    if iterations is None:
        per_iter = (
            config.num_formations * env_params.num_agents * ppo.n_steps
        )
        iterations = -(-default_total_timesteps(config) // per_iter)
    return dataclasses.replace(
        ppo, total_iterations=max(1, int(iterations))
    )


def make_ppo_iteration(
    env_params: EnvParams,
    ppo: PPOConfig,
    per_formation: bool = False,
    env_step_fn: Any = None,
    scenario_step_fn: Any = None,
):
    """Build the functional training iteration: rollout + GAE + all
    minibatch epochs as one pure function
    ``(train_state, env_state, obs, key) -> (train_state, env_state,
    last_obs, key, metrics)``.

    Module-level (not a Trainer method) so other shells can transform it:
    ``Trainer`` jits it directly; ``SweepTrainer`` (train/sweep.py) vmaps
    it over a population of seeds before jitting.

    ``scenario_step_fn`` (``scenarios.make_scenario_step``) routes env
    stepping through the disturbance stack; the iteration then takes the
    batched ``ScenarioParams`` as a fifth, *traced* argument — severity
    schedules and per-formation scenario mixes are pure data, so the
    compiled program never changes (tests/test_scenarios.py pins the
    compile-once contract).
    """
    if per_formation:
        # Minibatch whole formations: rows are (N, ...) blocks so the
        # centralized critic sees every agent. batch_size stays denominated
        # in agent-transitions for comparable SGD noise across policies.
        n = env_params.num_agents
        update_ppo = dataclasses.replace(
            ppo, batch_size=max(1, ppo.batch_size // n)
        )
        row_shape = (n,)
    else:
        update_ppo = ppo
        row_shape = ()

    def iteration(
        train_state: TrainState,
        env_state,
        obs: Array,
        key: Array,
        *scenario_args,
    ) -> Tuple[TrainState, Any, Array, Array, Dict[str, Array]]:
        if scenario_step_fn is not None:
            (scenario_params,) = scenario_args
            step_fn = lambda s, v: scenario_step_fn(s, v, scenario_params)  # noqa: E731
        else:
            step_fn = env_step_fn
        key, k_roll, k_update = jax.random.split(key, 3)
        with jax.named_scope("rollout"):
            env_state, last_obs, batch, last_value = collect_rollout(
                train_state.apply_fn,
                train_state.params,
                env_state,
                obs,
                k_roll,
                env_params,
                ppo.n_steps,
                env_step_fn=step_fn,
            )
        with jax.named_scope("gae"):
            advantages, returns = compute_gae(
                batch.rewards,
                batch.values,
                batch.dones,
                last_value,
                ppo.gamma,
                ppo.gae_lambda,
            )
        flat = MinibatchData(
            obs=batch.obs.reshape(-1, *row_shape, env_params.obs_dim),
            actions=batch.actions.reshape(
                -1, *row_shape, env_params.act_dim
            ),
            old_log_probs=batch.log_probs.reshape(-1, *row_shape),
            advantages=advantages.reshape(-1, *row_shape),
            returns=returns.reshape(-1, *row_shape),
        )
        with jax.named_scope("ppo_update"):
            train_state, update_metrics = ppo_update(
                train_state, flat, k_update, update_ppo
            )
        metrics = {
            k: v.mean() for k, v in batch.metrics.items()
        }
        metrics.update(update_metrics)
        metrics["reward"] = batch.rewards.mean()
        # Formation-level episode count (batch.dones broadcasts the
        # per-formation done to all N agent rows; same reduction as
        # HeteroTrainer so the metric's unit matches across trainers).
        metrics["episode_dones"] = batch.dones[..., 0].sum()
        return train_state, env_state, last_obs, key, metrics

    return iteration


def make_fused_chunk(iteration, k: int, reduce_metrics: bool = False):
    """Fuse ``k`` rollout+update iterations into ONE ``lax.scan`` device
    program — the Podracer "Anakin" dispatch shape (PAPERS.md): the carry
    is the full training state ``(train_state, env_state, obs, key)``
    (donated by the caller's jit), the host touches the device once per
    chunk, and per-iteration metrics come back stacked along a leading
    ``(k,)`` axis so a whole chunk's telemetry drains in one batched
    ``device_get``.

    Scenario params, when present, ride as the scan's xs with a leading
    ``(k,)`` axis — every fused iteration trains at its own schedule
    point, exactly like ``k`` host-loop dispatches (bitwise; pinned by
    tests/test_fused_scan.py).

    ``reduce_metrics=True`` keeps the legacy burst contract
    (``TrainConfig.iters_per_dispatch``: mean over the chunk,
    ``episode_dones`` sums) for the single-run ``Trainer``'s host-loop
    burst spelling — its ONLY remaining consumer now that both population
    sweeps dispatch through the stacked-metrics fused path. This replaces
    the former ``_burst`` helper — one scan builder serves both cadences,
    so the two can never drift.
    """

    def fused_chunk_iteration(train_state, env_state, obs, key, *scenario_seq):
        def body(carry, xs):
            train_state, env_state, obs, key = carry
            extra = () if xs is None else (xs,)
            train_state, env_state, obs, key, metrics = iteration(
                train_state, env_state, obs, key, *extra
            )
            return (train_state, env_state, obs, key), metrics

        xs = scenario_seq[0] if scenario_seq else None
        (train_state, env_state, obs, key), stacked = jax.lax.scan(
            body, (train_state, env_state, obs, key), xs, length=k
        )
        if reduce_metrics:
            # episode_dones sums; the health flags reduce by MIN (the
            # burst is healthy only if every fused iteration was — a
            # mean would dilute a single skip below detection); the
            # rest mean, the legacy burst contract.
            stacked = {
                name: (
                    v.sum(axis=0)
                    if name == "episode_dones"
                    else v.min(axis=0)
                    if name.startswith("health_")
                    else v.mean(axis=0)
                )
                for name, v in stacked.items()
            }
        return train_state, env_state, obs, key, stacked

    return fused_chunk_iteration


class Trainer:
    """Imperative shell around the functional training core.

    ``mesh_axes``/``mesh`` wiring for multi-chip sharding lives in
    ``parallel/``; pass ``shard_fn`` to place env state and train state on a
    device mesh — the jitted iteration is sharding-agnostic.
    """

    def __init__(
        self,
        env_params: EnvParams,
        ppo: PPOConfig = PPOConfig(),
        config: TrainConfig = TrainConfig(),
        model: Any = None,
        shard_fn: Any = None,
        scenario_schedule: Any = None,
    ) -> None:
        ppo = fill_ent_schedule(ppo, env_params, config)
        self.env_params = env_params
        # Env-generic dispatch (envs/): resolved from the params TYPE, so
        # formation params route to the legacy env/formation.py functions
        # verbatim (bitwise-identical path) and any registered env trains
        # through the same compiled program structure.
        self.env_spec = spec_for_params(env_params)
        self.ppo = ppo
        self.config = config
        self.num_envs = config.num_formations * env_params.num_agents

        self.model = model or MLPActorCritic(
            act_dim=env_params.act_dim, log_std_init=ppo.log_std_init
        )
        # Formation-level models (CTDE critic, GNN) must see whole
        # formations; agent-factored models (plain MLP) can be minibatched
        # over individual agent-transitions, as SB3 does.
        self.per_formation = getattr(self.model, "per_formation", False)

        key = jax.random.PRNGKey(config.seed)
        self.key, k_init, k_env = jax.random.split(key, 3)
        if self.per_formation:
            dummy_obs = jnp.zeros(
                (1, env_params.num_agents, env_params.obs_dim), jnp.float32
            )
        else:
            dummy_obs = jnp.zeros((1, env_params.obs_dim), jnp.float32)
        params = self.model.init(k_init, dummy_obs)
        # lr backoff needs the rate IN the optimizer state (pure data,
        # no recompile on a rollback) — inject only when the knob is
        # live so the default opt-state layout (and its checkpoints)
        # stays bit-identical.
        self.train_state = TrainState.create(
            apply_fn=self.model.apply,
            params=params,
            tx=ppo.make_optimizer(
                inject_lr=config.recovery_lr_backoff != 1.0
            ),
        )

        self._shard_fn = shard_fn
        # Agent-axis ('sp') sharding: swap the vmapped env step for the
        # sharded step (parallel/ring.py) so large swarms roll with N split
        # across devices — ring obs exchange one-agent halos (constant
        # per-device ICI traffic); knn obs all-gather positions and search
        # locally per slab.
        self._env_step_fn = None
        mesh = getattr(shard_fn, "mesh", None)
        if (
            mesh is not None or jax.process_count() > 1
        ) and self.env_spec.name != "formation":
            # The mesh-specialized steps (sp ring halo exchange, dp-mesh
            # shard_map knn) and the multi-host sharded reset are built
            # from formation functions — fail fast instead of silently
            # training the wrong env through them.
            raise SystemExit(
                f"env {self.env_spec.name!r} does not compose with mesh "
                "sharding / multi-host yet (the sharded env steps in "
                "parallel/ are formation-specialized); drop the mesh or "
                "use env=formation"
            )
        if mesh is not None and "sp" in mesh.shape:
            from marl_distributedformation_tpu.parallel import make_ring_step

            self._env_step_fn = make_ring_step(env_params, mesh)
        elif mesh is not None and env_params.obs_mode == "knn":
            # knn on a dp mesh: shard_map the env step so the Pallas
            # neighbor kernel sees its local block (the SPMD partitioner
            # cannot split a pallas_call; see parallel.make_dp_step).
            from marl_distributedformation_tpu.parallel import make_dp_step

            self._env_step_fn = make_dp_step(env_params, mesh)
        self._multihost = jax.process_count() > 1
        if self._multihost:
            # Multi-host: every process builds only its own formation shard
            # (parallel/distributed.py) — device_put onto a global mesh from
            # full host arrays is not possible across processes.
            assert shard_fn is not None and getattr(
                shard_fn, "mesh", None
            ), "multi-host training needs a mesh (cfg.mesh / make_shard_fn)"
            from marl_distributedformation_tpu.parallel import (
                replicate,
                reset_batch_sharded,
            )

            mesh = shard_fn.mesh
            self.env_state = reset_batch_sharded(
                k_env, env_params, config.num_formations, mesh
            )
            self.obs = jax.jit(
                functools.partial(compute_obs, params=env_params)
            )(self.env_state.agents, self.env_state.goal)
            self.train_state = replicate(self.train_state, mesh)
        else:
            self.env_state = self.env_spec.reset_batch(
                k_env, env_params, config.num_formations
            )
            # The spec's obs is shape-generic over the leading formation
            # axis and routes knn obs through the batched (Pallas-capable)
            # search — for formation these ARE reset_batch/compute_obs.
            self.obs = self.env_spec.obs(self.env_state, env_params)
            if shard_fn is not None:
                self.train_state, self.env_state, self.obs = shard_fn(
                    self.train_state, self.env_state, self.obs
                )

        # Scenario training (scenarios/, docs/scenarios.md): env stepping
        # routes through the disturbance stack and the iteration takes the
        # batched ScenarioParams as a traced argument — domain
        # randomization over the schedule's scenario set, severity ramps
        # per stage, zero recompiles across all of it.
        self._scenario_schedule = scenario_schedule
        self._scenario_step_fn = None
        self.scenario_params = None
        self.scenario_severity = 0.0
        # Recovery severity backoff (train/recovery.py): multiplies
        # every sampled severity; 1.0 (always, until a rollback with
        # recovery_severity_backoff != 1.0) keeps the sampling path
        # bitwise untouched. Set BEFORE the first resample below.
        self._severity_scale = 1.0
        # Per-iteration severities of the most recent chunked dispatch
        # (what the fused driver logs) — written by _next_scenario_chunk.
        self._last_chunk_severities = None
        # Auto-curriculum seam (scenarios/adversary.py, docs/adversarial.md):
        # a schedule handed to request_scenario_schedule() from another
        # thread (the pipeline supervisor feeding gate falsifiers back)
        # is applied at the next dispatch boundary — the only place the
        # training thread touches schedule state.
        self._pending_schedule: Any = None  # graftlock: guarded-by=_schedule_lock
        self._schedule_lock = threading.Lock()
        if scenario_schedule is not None:
            if self._env_step_fn is not None:
                # Which specialized step blocked it matters for the fix:
                # 'sp' meshes replace the env step wholesale; knn on a dp
                # mesh wraps it in shard_map — neither is scenario-wrapped.
                blocker = (
                    "the agent-axis ('sp') sharded ring step — drop 'sp' "
                    "from the mesh"
                    if "sp" in mesh.shape
                    else "the shard_map knn env step a dp mesh uses for "
                    "obs_mode=knn — use obs_mode=ring on this mesh, or "
                    "drop the mesh"
                )
                raise SystemExit(
                    f"scenario training does not compose with {blocker}; "
                    "scenarios currently wrap only the plain vmapped step"
                )
            if self._multihost:
                raise SystemExit(
                    "scenario training is single-host for now (per-host "
                    "scenario-param construction is not wired); drop "
                    "scenarios or run single-process"
                )
            from marl_distributedformation_tpu.scenarios import (
                get_scenario,
                make_scenario_step,
            )

            self._scenario_specs = tuple(
                get_scenario(n) for n in scenario_schedule.names
            )
            self._scenario_step_fn = make_scenario_step(env_params)
            self._build_scenario_samplers()
            # Base key for the sampling stream; per-dispatch keys fold in
            # the global rollout index, so the stream is a pure function
            # of (seed, rollout) and resume continues it exactly instead
            # of replaying the first dispatches' draws.
            self._scenario_base_key = jax.random.fold_in(
                jax.random.PRNGKey(config.seed), 0x5CE7
            )
            self._scenario_rollouts = 0
            # The key stream folds this GLOBAL draw counter, not the
            # schedule-relative rollout index: a curriculum swap resets
            # the schedule position but must never replay early-run
            # sampling keys. Identical to _scenario_rollouts until the
            # first update_scenario_schedule (bitwise parity with the
            # pre-feedback behavior, incl. fused==host pins).
            self._scenario_draws = 0
            self._resample_scenario_params()

        self.num_timesteps = 0
        self._vec_steps_since_save = 0
        self._iteration_core = self._make_iteration()
        # Self-healing train lane (train/recovery.py, docs/recovery.md):
        # the in-program health word + skip-update guard wrap the
        # functional core BEFORE fusion, so host-loop, burst, and fused
        # dispatch all carry the same flags in their metrics.
        if config.health:
            from marl_distributedformation_tpu.train.recovery import (
                wrap_health,
            )

            self._iteration_core = wrap_health(
                self._iteration_core, config
            )
        self.halted = False
        self.recovery_ladder = None
        self._recovery_verdict: Optional[str] = None
        self._last_good_ckpt: Optional[Path] = None
        self._rollback_anchor: Optional[Dict[str, Any]] = None
        if config.recovery:
            if not config.health:
                raise SystemExit(
                    "recovery=true needs health=true — the escalation "
                    "ladder consumes the in-program health flags at the "
                    "drain seam; without them it is blind"
                )
            if self._multihost:
                raise SystemExit(
                    "the recovery ladder is single-host for now "
                    "(rollback restore has no cross-host broadcast "
                    "seam); drop recovery or run single-process"
                )
            from marl_distributedformation_tpu.train.recovery import (
                RecoveryConfig,
                RecoveryLadder,
            )

            self.recovery_ladder = RecoveryLadder(
                RecoveryConfig(
                    breach_iters=config.recovery_breach_iters,
                    max_rollbacks=config.recovery_max_rollbacks,
                    lr_backoff=config.recovery_lr_backoff,
                    severity_backoff=config.recovery_severity_backoff,
                ),
                config.log_dir or str(repo_root() / "logs" / config.name),
            )
        self._iters_per_dispatch = max(1, int(config.iters_per_dispatch))
        self._fused_chunk = max(0, int(config.fused_chunk))
        if self._fused_chunk and self._iters_per_dispatch > 1:
            raise SystemExit(
                "fused_chunk and iters_per_dispatch are two spellings of "
                "dispatch fusion — set exactly one (fused_chunk is the "
                "Anakin mode: stacked per-iteration metrics, double-"
                "buffered drain, background checkpoints; "
                "iters_per_dispatch is the host-loop burst)"
            )
        if self._fused_chunk and self._multihost:
            raise SystemExit(
                "fused-scan training is single-host for now (the async "
                "checkpoint writer has no cross-host durability barrier); "
                "drop fused_chunk or run single-process"
            )
        if self._fused_chunk:
            dispatch_fn = make_fused_chunk(
                self._iteration_core, self._fused_chunk
            )
        elif self._iters_per_dispatch > 1:
            dispatch_fn = make_fused_chunk(
                self._iteration_core,
                self._iters_per_dispatch,
                reduce_metrics=True,
            )
        else:
            dispatch_fn = self._iteration_core
        # Retrace guard (analysis/guards.py): counts every compilation of
        # the outermost jitted dispatch; with guard_retraces=N the trace
        # that exceeds N raises RetraceError naming the drifting argument
        # signature. Always counting (budget or not) costs one Python
        # closure call per COMPILE, i.e. nothing per step.
        self.retrace_guard = profiling.RetraceGuard(
            "train_iteration",
            max_traces=config.guard_retraces or None,
        )
        # ledgered_jit == jax.jit(guard.wrap(fn)) + automatic
        # ProgramLedger registration of the compiled executable (cost/
        # memory facts, build timings, per-dispatch latency) — the
        # obs/ledger.py seam every budget-1 compile site shares.
        self._iteration = profiling.ledgered_jit(
            dispatch_fn,
            self.retrace_guard,
            subsystem="trainer",
            program="train_iteration",
            donate_argnums=(0, 1),
        )
        self._dispatches = 0

        self.log_dir = config.log_dir or str(
            repo_root() / "logs" / config.name
        )
        # Optional checkpoint-durability hook (the always-learning
        # pipeline sets it to nudge its CheckpointStream): called with
        # the path AFTER the atomic rename lands — for async writes that
        # is on the writer thread, when the file is discoverable, not at
        # submit time (the bytes are still in flight then).
        self.on_checkpoint: Optional[Any] = None

        if config.resume:
            self._try_resume()
        if self.recovery_ladder is not None:
            # Last-resort rollback target: a host copy of the run's
            # starting state (post-resume), so divergence BEFORE the
            # first checkpoint still recovers instead of halting with
            # nothing to restore.
            self._rollback_anchor = jax.device_get(
                self._checkpoint_target()
            )

    # ------------------------------------------------------------------
    # Functional core
    # ------------------------------------------------------------------

    def _make_iteration(self):
        return make_ppo_iteration(
            self.env_params,
            self.ppo,
            self.per_formation,
            self._env_step_fn,
            self._scenario_step_fn,
        )

    def _build_scenario_samplers(self) -> None:
        """(Re)build the jitted domain-randomization samplers over the
        schedule's CURRENT spec union: stage changes move probability
        mass, severity ramps scale magnitudes — both traced, so each
        sampler compiles once per spec union. The chunked twin draws a
        whole fused chunk's per-iteration batches in one pass (leading
        (k,) axis over keys/severities/probs)."""
        from marl_distributedformation_tpu.scenarios import (
            sample_scenario_batch,
        )

        # The samplers are tiny jitted programs but programs all the
        # same: they register in the ProgramLedger under a persistent
        # count-only guard that survives schedule-swap rebuilds, so
        # every sampler compile stays an attributed census entry (and
        # the entry-count == receipt-count invariant holds).
        if not hasattr(self, "_sampler_guard"):
            self._sampler_guard = profiling.RetraceGuard("scenario_sampler")
        self._sample_scenarios = profiling.ledgered_jit(
            functools.partial(
                sample_scenario_batch,
                specs=self._scenario_specs,
                num_formations=self.config.num_formations,
            ),
            self._sampler_guard,
            subsystem="scenarios",
            program="scenario_sampler",
        )
        self._sample_scenario_chunk = profiling.ledgered_jit(
            jax.vmap(
                functools.partial(
                    sample_scenario_batch,
                    specs=self._scenario_specs,
                    num_formations=self.config.num_formations,
                )
            ),
            self._sampler_guard,
            subsystem="scenarios",
            program="scenario_sampler_chunk",
        )

    def update_scenario_schedule(self, schedule: Any) -> None:
        """Swap the training curriculum mid-run (the auto-curriculum
        seam: ``scenarios.from_falsifiers`` schedules land here).

        The expensive compiled artifact — the train-step / fused-chunk
        program — is untouched by ANY schedule change: ``ScenarioParams``
        ride as traced inputs with fixed shapes, so stage tables,
        severities, and spec magnitudes are pure data (pinned by
        tests/test_adversary.py with a budget-1 RetraceGuard across the
        swap). Only the tiny jitted SAMPLER is rebuilt, and only when
        the spec set changed by VALUE — expect that on every feedback
        round (a re-fed ``adv:`` spec carries new falsifier magnitudes),
        a milliseconds-scale host re-jit off the compiled train path;
        what the stable ``adv:`` names buy is a fixed spec-union SIZE
        (the sampler's stacked axis and the registry never grow across
        rounds). The new schedule starts at its own rollout 0; the
        sampling key stream folds a separate global draw counter that is
        never reset, so feedback rounds cannot replay early-run draws.
        Call from the training thread (or between dispatches) — other
        threads use :meth:`request_scenario_schedule`.
        """
        if self._scenario_schedule is None:
            raise ValueError(
                "this trainer was built without scenario training — the "
                "compiled step takes no scenario input, so a schedule "
                "cannot be installed mid-run (construct the trainer with "
                "scenarios=['clean'] to reserve the traced seam, then "
                "update freely)"
            )
        from marl_distributedformation_tpu.scenarios import get_scenario

        new_specs = tuple(get_scenario(n) for n in schedule.names)
        if new_specs != self._scenario_specs:
            self._scenario_specs = new_specs
            self._build_scenario_samplers()
        self._scenario_schedule = schedule
        self._scenario_rollouts = 0
        self._resample_scenario_params()

    def request_scenario_schedule(self, schedule: Any) -> None:
        """Thread-safe curriculum handoff: stash ``schedule`` for the
        training thread to apply at its next dispatch boundary (the
        pipeline supervisor's feedback path — it must never mutate
        sampler state while a dispatch is being prepared). Validates
        eagerly so the CALLER gets the error, not the training loop."""
        if self._scenario_schedule is None:
            raise ValueError(
                "this trainer was built without scenario training — "
                "construct it with scenarios=['clean'] to reserve the "
                "traced scenario seam for curriculum feedback"
            )
        from marl_distributedformation_tpu.scenarios import get_scenario

        for name in schedule.names:
            get_scenario(name)  # unknown names fail in the caller
        with self._schedule_lock:
            self._pending_schedule = schedule

    def _apply_pending_schedule(self) -> None:
        if self._pending_schedule is None:
            return
        with self._schedule_lock:
            pending, self._pending_schedule = self._pending_schedule, None
        if pending is not None:
            self.update_scenario_schedule(pending)

    def _resample_scenario_params(self) -> None:
        """Redraw the per-formation scenario mix at the schedule's current
        severity (called per dispatch — fresh domain randomization every
        rollout, values-only so the train step never retraces)."""
        schedule = self._scenario_schedule
        self.scenario_severity = schedule.severity_at(self._scenario_rollouts)
        if self._severity_scale != 1.0:
            # Recovery severity backoff (train/recovery.py): pure data,
            # applied at the sampling seam — the schedule object itself
            # stays untouched so a later scale reset is exact.
            self.scenario_severity = (
                self.scenario_severity * self._severity_scale
            )
        k_sample = jax.random.fold_in(
            self._scenario_base_key, self._scenario_draws
        )
        self.scenario_params = self._sample_scenarios(
            k_sample,
            jnp.float32(self.scenario_severity),
            jnp.asarray(schedule.probs_at(self._scenario_rollouts)),
        )

    def _next_scenario_chunk(self, k: int):
        """Stacked ``ScenarioParams`` (leading ``(k,)`` axis) for the next
        ``k`` rollouts ``[r0, r0+k)`` — the scan's xs for a fused chunk.
        Keys fold in each GLOBAL draw index (== the rollout index until a
        curriculum swap; never reset, so feedback rounds cannot replay
        early-run draws) and severities/probs come off the schedule per
        iteration, so every scanned iteration trains at exactly the
        params the host loop would have drawn at its rollout index
        (bitwise; tests/test_fused_scan.py) and resume re-enters
        mid-schedule unchanged. One jitted pass, values-only: stage
        changes and severity ramps never retrace. The severity row is
        kept on ``_last_chunk_severities`` so the fused driver logs the
        EXACT values this chunk trains at (no second schedule read that
        a concurrent curriculum swap could race)."""
        schedule = self._scenario_schedule
        r0 = self._scenario_rollouts
        d0 = self._scenario_draws
        keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            self._scenario_base_key, jnp.arange(d0, d0 + k)
        )
        severities = schedule.severity_chunk(r0, k)
        if self._severity_scale != 1.0:
            # Recovery severity backoff: scale the whole chunk's row;
            # the stash below then logs the severities ACTUALLY trained.
            severities = [s * self._severity_scale for s in severities]
        self._last_chunk_severities = severities
        return self._sample_scenario_chunk(
            keys,
            jnp.asarray(severities),
            jnp.asarray(schedule.probs_chunk(r0, k)),
        )

    # ------------------------------------------------------------------
    # Imperative shell
    # ------------------------------------------------------------------

    @property
    def total_timesteps(self) -> int:
        return default_total_timesteps(self.config)

    def _dispatch(self, rollouts: int) -> Dict[str, Array]:
        """Dispatch the jitted program once (``rollouts`` iterations of
        training), under the opt-in runtime guards, and advance the host
        counters. Shared by the host-loop and fused-scan shells."""
        self._apply_pending_schedule()
        # Train-lane chaos seams (chaos/plane.py, docs/chaos.md): a
        # 'raise' armed at the poison points is interpreted HERE, at the
        # dispatch boundary, as state corruption — a NaN bomb into the
        # carry, or a finite 1e18 scale whose gradients explode — the
        # deterministic stand-ins for organic divergence the health word
        # + recovery ladder exist to absorb. Host-side only (rule 19).
        try:
            fault_point("train.carry_poison")
        except InjectedFault:
            self._poison_carry(float("nan"))
        try:
            fault_point("train.grad_bomb")
        except InjectedFault:
            self._poison_carry(1.0e18)
        with contextlib.ExitStack() as stack:
            if self.config.guard_transfers and self._dispatches > 0:
                # Post-warmup only: the compile dispatch legitimately
                # uploads trace-time constants; from the second dispatch
                # on, any device->host sync in here is a hot-loop bug.
                stack.enter_context(profiling.no_host_transfers())
            if self.config.guard_nans:
                stack.enter_context(profiling.nan_guard())
            if self.scenario_params is None:
                extra = ()
            elif self._fused_chunk or rollouts > 1:
                # Chunked dispatch (any fused_chunk — a K=1 scan still
                # takes xs with a leading (1,) axis — or a legacy burst):
                # each scanned iteration gets the params the host loop
                # would draw at its rollout index, resampled per
                # iteration — not one batch frozen across the chunk.
                extra = (self._next_scenario_chunk(rollouts),)
            else:
                extra = (self.scenario_params,)
            (
                self.train_state,
                self.env_state,
                self.obs,
                self.key,
                metrics,
            ) = self._iteration(
                self.train_state, self.env_state, self.obs, self.key, *extra
            )
        self._dispatches += 1
        # Live-metrics plane (obs/metrics.py, docs/observability.md):
        # recorded at the dispatch seam, never under trace (graftlint
        # rule 18). Two dict ops per dispatch — noise next to a rollout.
        get_registry().counter("train_iterations_total").inc(rollouts)
        self.num_timesteps += rollouts * self.ppo.n_steps * self.num_envs
        self._vec_steps_since_save += rollouts * self.ppo.n_steps
        if self._scenario_schedule is not None:
            self._scenario_rollouts += rollouts
            self._scenario_draws += rollouts
            if not self._fused_chunk and rollouts == 1:
                # Chunked modes draw their params from
                # _next_scenario_chunk at dispatch time — resampling the
                # single-dispatch batch here would be one wasted device
                # program per chunk on the hot path.
                self._resample_scenario_params()
        return metrics

    def run_iteration(self) -> Dict[str, float]:
        """One host-loop dispatch — ``iters_per_dispatch`` rollout+update
        cycles (1 by default); returns device metrics (burst-averaged
        when fused)."""
        assert not self._fused_chunk, (
            "fused_chunk trainers dispatch via run_chunk() (stacked "
            "per-iteration metrics), not run_iteration()"
        )
        return self._dispatch(self._iters_per_dispatch)

    def run_chunk(self) -> Dict[str, Array]:
        """Anakin mode: dispatch ONE fused-scan chunk (``fused_chunk``
        iterations) and return the per-iteration metrics stack as DEVICE
        arrays (leading ``(k,)`` axis). The call returns as soon as the
        program is enqueued — the caller overlaps the host drain of the
        previous chunk with this one's execution (see ``_train_fused``)."""
        assert self._fused_chunk > 0, (
            "run_chunk() needs fused_chunk > 0 (Anakin mode)"
        )
        return self._dispatch(self._fused_chunk)

    def train(self) -> Dict[str, float]:
        """Full training run with metrics + checkpoints; returns the last
        emitted metrics record."""
        if self._fused_chunk:
            return self._train_fused()
        logger = MetricsLogger(
            self.log_dir,
            run_name=self.config.name,
            use_wandb=self.config.use_wandb,
            use_tensorboard=self.config.use_tensorboard,
        )
        meter = Throughput()
        last_record: Dict[str, float] = {}
        iteration = 0
        # profile=true: trace a few post-warmup dispatches (the first is
        # compile-bound and would dominate the trace).
        tracer = profiling.TraceWindow(
            self.log_dir, self.config.profile, self.config.profile_iterations
        )
        try:
            while self.num_timesteps < self.total_timesteps and (
                not self.halted
            ):
                tracer.before_dispatch()
                metrics = self.run_iteration()
                iteration += 1
                tracer.after_dispatch(metrics)
                meter.tick(
                    self._iters_per_dispatch
                    * self.ppo.n_steps
                    * self.config.num_formations
                )
                # Live gauges every dispatch (three dict writes), not
                # just at log cadence — GET /metrics must answer "how
                # fast right now" even when log_interval is long.
                self._record_lane_metrics(meter.rate())
                if iteration % self.config.log_interval == 0:
                    # One host sync per log interval, after dispatch — a
                    # single batched device_get, NOT per-metric float():
                    # on a tunneled TPU each transfer pays full RTT, and
                    # ~16 of them per iteration can cost more than the
                    # iteration itself. The health flags ride the SAME
                    # sync — never a per-iteration finiteness probe
                    # (graftlint rule 22), so with log_interval > 1 the
                    # host-loop ladder observes at log cadence.
                    host_metrics = jax.device_get(metrics)
                    if self._observe_health(host_metrics, iteration):
                        # Rolled back (or halted): the state was
                        # restored; this dispatch's record is poisoned
                        # telemetry — drop it and continue/stop.
                        continue
                    last_record = {
                        k: float(v) for k, v in host_metrics.items()
                    }
                    last_record["env_steps_per_sec"] = meter.rate()
                    if self._scenario_schedule is not None:
                        # Severity of the NEXT dispatch was already
                        # resampled; record the one this metrics batch
                        # actually trained at.
                        last_record["scenario_severity"] = float(
                            self._scenario_schedule.severity_at(
                                max(
                                    self._scenario_rollouts
                                    - self._iters_per_dispatch,
                                    0,
                                )
                            )
                        )
                    logger.log(last_record, self.num_timesteps)
                if (
                    self.config.checkpoint
                    and self._vec_steps_since_save >= self.config.save_freq
                ):
                    if (
                        self.recovery_ladder is not None
                        and iteration % self.config.log_interval != 0
                    ):
                        # With log_interval > 1 this dispatch's flags
                        # were never drained — and publishing an
                        # unobserved state can mint a finite-but-
                        # poisoned checkpoint at a newer step per save,
                        # outrunning the quarantine walk. The save
                        # boundary is already an IO seam, so one small
                        # flag pull here is not the per-iteration probe
                        # rule 22 bans.
                        flags = jax.device_get({
                            k: metrics[k]
                            for k in ("health_ok", "health_word")
                            if k in metrics
                        })
                        if self._observe_health(flags, iteration):
                            continue  # rolled back: nothing to save
                    if not self._saves_suspended():
                        self.save()
            if self.recovery_ladder is not None and not self.halted:
                # Run-end guarantee, host-loop flavor (the fused driver
                # has its own call): finite final params even when a
                # tail poison never tripped the ladder.
                self._ensure_finite_final_state(None, iteration)
            if self.config.checkpoint and not self._saves_suspended():
                # The final save honors the suspect window too: a
                # finite-but-diverged tail state (shorter than
                # breach_iters) must not become the newest discoverable
                # checkpoint — the last-good file already on disk is
                # the state worth resuming.
                self.save()
        finally:
            tracer.close()
            logger.close()
        return last_record

    # ------------------------------------------------------------------
    # Anakin mode (fused_chunk > 0): whole-loop scan dispatch with an
    # async metrics drain and a background checkpoint pipeline
    # (docs/training.md "Anakin mode").
    # ------------------------------------------------------------------

    def _train_fused(self) -> Dict[str, float]:
        """Fused-scan driver: dispatch chunk N+1 BEFORE draining chunk
        N's metrics (double-buffered — the device computes while the host
        logs), and checkpoint at chunk boundaries on a background writer
        thread off a device-side snapshot. The emitted records are
        per-iteration, identical to the host loop's (log_interval honored
        on the global iteration index)."""
        logger = MetricsLogger(
            self.log_dir,
            run_name=self.config.name,
            use_wandb=self.config.use_wandb,
            use_tensorboard=self.config.use_tensorboard,
        )
        meter = Throughput()
        writer = (
            AsyncCheckpointWriter(
                keep_last_n=self.config.keep_last_n,
                protect=self._protected_paths,
            )
            if self.config.checkpoint
            else None
        )
        # Chunk-granular profile=true: trace profile_iterations whole
        # chunks post-warmup — one dispatch is one chunk here.
        tracer = profiling.TraceWindow(
            self.log_dir, self.config.profile, self.config.profile_iterations
        )
        last_record: Dict[str, float] = {}
        k = self._fused_chunk
        iteration = 0
        pending = None  # the chunk in flight, drained one dispatch later
        try:
            while self.num_timesteps < self.total_timesteps and (
                not self.halted
            ):
                steps_before = self.num_timesteps
                tracer.before_dispatch()
                stacked = self.run_chunk()
                tracer.after_dispatch(stacked)
                # The severities this chunk ACTUALLY trained at — stashed
                # by _next_scenario_chunk inside the dispatch, after any
                # pending curriculum swap was applied, so a feedback
                # schedule landing concurrently can never desync the
                # logged severities from the trained ones.
                severities = self._last_chunk_severities
                if pending is not None:
                    last_record = (
                        self._drain_chunk(logger, meter, *pending)
                        or last_record
                    )
                    if self._act_on_recovery_verdict(writer, iteration):
                        # Rolled back (or halted): the chunk just
                        # dispatched trained FROM the diverged state —
                        # abandon it undrained and restart the pipeline
                        # from the restored state.
                        pending = None
                        continue
                pending = (stacked, iteration, steps_before, severities)
                iteration += k
                if (
                    writer is not None
                    and self._vec_steps_since_save >= self.config.save_freq
                    and not self._saves_suspended()
                ):
                    self.save_async(writer)
            if pending is not None:
                last_record = (
                    self._drain_chunk(logger, meter, *pending) or last_record
                )
                self._act_on_recovery_verdict(writer, iteration)
            if self.recovery_ladder is not None and not self.halted:
                # Terminal guarantee: the run must END on finite params
                # even when the budget expired mid-breach (a tail poison
                # shorter than breach_iters never trips the ladder). ONE
                # host check at run end — never inside the dispatch loop.
                self._ensure_finite_final_state(writer, iteration)
            if writer is not None:
                if not self._saves_suspended():
                    # Suspect tail states stay unpublished (see the
                    # host loop's final save) — the ring's last-good
                    # file is the resume point.
                    self.save_async(writer)
                writer.close()  # the final write is durable before return
                writer = None
        finally:
            tracer.close()
            if writer is not None:
                # Unwinding on an error: drain the writer without letting
                # a secondary write failure mask the original exception.
                writer.close_quietly()
            logger.close()
        return last_record

    def _record_lane_metrics(self, env_steps_rate: float) -> None:
        """Publish this lane's throughput gauges into the process
        registry (the ``GET /metrics`` namespace): env-steps/s,
        train-steps/s, and the live RetraceGuard compile counter —
        what ROADMAP item 3's autoscaler and the RegressionSentinel
        watch. Host-seam only (the drain, after device_get)."""
        registry = get_registry()
        registry.gauge("train_env_steps_per_sec").set(env_steps_rate)
        per_iter = self.ppo.n_steps * self.config.num_formations
        registry.gauge("train_steps_per_sec").set(
            env_steps_rate / per_iter if per_iter else 0.0
        )
        registry.gauge("train_compiles").set(self.retrace_guard.count)

    def _drain_chunk(
        self, logger, meter, stacked, first_iteration, steps_before,
        severities,
    ) -> Dict[str, float]:
        """ONE batched ``device_get`` for a whole chunk's telemetry, then
        emit per-iteration records exactly like the host loop would.
        Called after the NEXT chunk has been dispatched, so this blocks on
        the finished chunk while the device already runs the new one."""
        t_drain = time.perf_counter()
        host = jax.device_get(stacked)
        meter.tick(
            self._fused_chunk * self.ppo.n_steps * self.config.num_formations
        )
        registry = get_registry()
        registry.histogram("train_chunk_drain_seconds").observe(
            time.perf_counter() - t_drain
        )
        registry.counter("train_chunks_total").inc()
        # Device-memory watermark at the drain boundary: the one host
        # seam per chunk where a sync just happened anyway, so the
        # sample costs no extra pipeline stall (obs/ledger.py).
        profiling.sample_device_watermark()
        self._record_lane_metrics(meter.rate())
        if "health_ok" in host:
            # The drain seam IS the detection seam: the health flags
            # arrived in the same batched device_get as the rest of the
            # chunk telemetry (zero extra syncs), so a divergence is
            # seen within ONE chunk drain of the poisoned dispatch. The
            # ladder's verdict is acted on by the driver loop (it owns
            # the in-flight chunk and the writer).
            if self.recovery_ladder is not None:
                self._recovery_verdict = self.recovery_ladder.observe(
                    host["health_ok"],
                    host.get("health_word"),
                    first_iteration,
                )
            else:
                from marl_distributedformation_tpu.train.recovery import (
                    record_health_flags,
                )

                record_health_flags(host)
        per_iter = self.ppo.n_steps * self.num_envs
        last_record: Dict[str, float] = {}
        for i in range(self._fused_chunk):
            if (first_iteration + i + 1) % self.config.log_interval:
                continue
            record = {name: float(v[i]) for name, v in host.items()}
            record["env_steps_per_sec"] = meter.rate()
            if severities is not None:
                record["scenario_severity"] = float(severities[i])
            logger.log(record, steps_before + (i + 1) * per_iter)
            last_record = record
        return last_record

    # ------------------------------------------------------------------
    # Recovery ladder actions (train/recovery.py, docs/recovery.md)
    # ------------------------------------------------------------------

    def _saves_suspended(self) -> bool:
        """Checkpoint cadence gate: while the ladder's most recent
        observation ended unhealthy, submit NOTHING. A finite-but-
        diverged state (grad bomb) passes the non-finite write gate;
        writing one per chunk would hand every rollback a fresh copy of
        the poison at an ever-newer step, defeating the quarantine-on-
        retarget walk. The first poisoned pre-detection write is
        unavoidable (detection lags one chunk) — that one file is
        exactly what the walk quarantines."""
        return (
            self.recovery_ladder is not None
            and self.recovery_ladder.suspect
        )

    def _poison_carry(self, value: float) -> None:
        """Chaos effect for the ``train.carry_poison`` / ``train.
        grad_bomb`` seams: corrupt the LIVE device params at the
        dispatch boundary (NaN kills the loss; a finite 1e18 scale
        explodes the gradients) — the deterministic stand-in for
        organic divergence."""
        poison = jnp.float32(value)
        self.train_state = self.train_state.replace(
            params=jax.tree_util.tree_map(
                lambda p: p * poison, self.train_state.params
            )
        )

    def _observe_health(self, host_metrics, iteration: int) -> bool:
        """Host-loop seam: feed the just-synced health flags to the
        ladder and act on its verdict. Returns True when the state was
        restored (rollback or halt) — the caller drops the poisoned
        record and continues (or stops)."""
        if "health_ok" not in host_metrics:
            return False
        if self.recovery_ladder is None:
            from marl_distributedformation_tpu.train.recovery import (
                record_health_flags,
            )

            record_health_flags(host_metrics)
            return False
        self._recovery_verdict = self.recovery_ladder.observe(
            host_metrics["health_ok"],
            host_metrics.get("health_word"),
            iteration,
        )
        return self._act_on_recovery_verdict(None, iteration)

    def _act_on_recovery_verdict(
        self, writer: Optional[AsyncCheckpointWriter], iteration: int
    ) -> bool:
        """Consume the verdict the last drain stored; perform the
        rollback / halt. Returns True when state was restored."""
        verdict, self._recovery_verdict = self._recovery_verdict, None
        if verdict in (None, "ok"):
            return False
        if verdict == "rollback":
            self._perform_rollback(writer, iteration)
            return True
        self._perform_rollback(
            writer,
            iteration,
            halt_reason=(
                "sustained divergence with the rollback budget "
                f"exhausted ({self.recovery_ladder.recoveries} "
                "recoveries spent)"
            ),
        )
        return True

    def _perform_rollback(
        self,
        writer: Optional[AsyncCheckpointWriter],
        iteration: int,
        halt_reason: Optional[str] = None,
    ) -> None:
        """Restore the newest VALID last-good state (checkpoint walk, or
        the run-start anchor when none exists), advance the PRNG stream
        past the divergence via the folded recovery counter, and apply
        the configured lr/severity backoff. With ``halt_reason`` the
        restore is terminal: the run ends here, on finite params, with
        a flight record."""
        from marl_distributedformation_tpu.train.recovery import (
            fold_recovery_key,
            scale_injected_lr,
        )
        from marl_distributedformation_tpu.utils.checkpoint import (
            quarantine_checkpoint,
        )

        t0 = time.perf_counter()
        ladder = self.recovery_ladder
        if writer is not None:
            try:
                # Join the in-flight write: it may be publishing the very
                # last-good file the walk below should find (or skipping
                # a poisoned one — the non-finite gate's audit trail owns
                # that).
                writer.wait()
            except RuntimeError:
                pass  # a failed WRITE must never block recovery; the
                #   skip/quarantine audit trail already recorded it
        found = None
        if self.config.checkpoint:
            for _ in range(8):
                found = restore_latest_partial(
                    self.log_dir, self._checkpoint_target()
                )
                if (
                    found is not None
                    and ladder is not None
                    and ladder.last_rollback_path == str(found[0])
                ):
                    # The previous rollback restored THIS file and the
                    # run re-diverged without any healthy progress: the
                    # checkpoint itself carries the poison (finite-but-
                    # diverged params slip past the non-finite write
                    # gate). Quarantine it and walk further back.
                    quarantine_checkpoint(
                        found[0],
                        "rollback target re-diverged (finite but "
                        "unhealthy state); walking back",
                    )
                    found = None
                    continue
                break
        if found is not None:
            path, restored = found
        else:
            path, restored = None, dict(self._rollback_anchor)
        restored = own_restored(restored)
        self.train_state = self.train_state.replace(
            params=restored["params"],
            opt_state=restored.get("opt_state", self.train_state.opt_state),
        )
        if "key" in restored:
            self.key = jnp.asarray(restored["key"])
        self.num_timesteps = int(restored["num_timesteps"])
        if "env_state" in restored:
            self.env_state = restored["env_state"]
            self.obs = restored["obs"]
        if self._shard_fn is not None:
            self.train_state, self.env_state, self.obs = self._shard_fn(
                self.train_state, self.env_state, self.obs
            )
        recoveries_next = (ladder.recoveries if ladder is not None else 0) + 1
        # The retry must not bitwise-replay the divergence: fold the
        # recovery counter into the restored key (deterministic — retry
        # N from checkpoint C is a pure function of (C, N)).
        self.key = fold_recovery_key(self.key, recoveries_next)
        lr_scale = None
        if self.config.recovery_lr_backoff != 1.0:
            scaled = scale_injected_lr(
                self.train_state.opt_state, self.config.recovery_lr_backoff
            )
            if scaled is not None:
                self.train_state = self.train_state.replace(opt_state=scaled)
                lr_scale = self.config.recovery_lr_backoff
            else:
                from marl_distributedformation_tpu.obs import get_tracer

                get_tracer().incident(
                    "train_lr_backoff_unavailable",
                    detail="opt state carries no injected learning_rate "
                    "leaf; backoff skipped",
                )
        severity_scale = None
        if (
            self.config.recovery_severity_backoff != 1.0
            and self._scenario_schedule is not None
        ):
            self._severity_scale *= self.config.recovery_severity_backoff
            severity_scale = self._severity_scale
        if self._scenario_schedule is not None:
            self._scenario_rollouts = self.num_timesteps // (
                self.ppo.n_steps * self.num_envs
            )
            # The draw counter NEVER rewinds (the no-replay law the
            # curriculum feedback loop already obeys) — the retry draws
            # fresh domain randomization instead of replaying the
            # possibly-divergence-inducing draws.
            self._scenario_draws = max(
                self._scenario_draws, self._scenario_rollouts
            )
            self._resample_scenario_params()
        self._vec_steps_since_save = 0
        if path is not None:
            self._last_good_ckpt = Path(path)
        mttr_s = time.perf_counter() - t0
        if ladder is None:
            return
        if halt_reason is None:
            ladder.note_rollback(
                to_step=self.num_timesteps,
                path=str(path) if path is not None else None,
                mttr_s=mttr_s,
                iteration=iteration,
                lr_scale=lr_scale,
                severity_scale=severity_scale,
            )
        else:
            ladder.note_halt(iteration, halt_reason)
            self.halted = True

    def _ensure_finite_final_state(
        self, writer: Optional[AsyncCheckpointWriter], iteration: int
    ) -> None:
        """Run-end guarantee: finite final params, even when the budget
        expired mid-breach (a tail poison shorter than breach_iters
        never trips the ladder; this terminal restore may exceed the
        retry budget by one — it is a guarantee, not a retry). One host
        pull, outside the dispatch loop."""
        from marl_distributedformation_tpu.utils.checkpoint import (
            nonfinite_leaf,
        )

        if nonfinite_leaf(
            jax.device_get(self.train_state.params)
        ) is not None:
            self._perform_rollback(writer, iteration)

    def _protected_paths(self):
        """Retention-ring protection set: the ladder's current last-good
        rollback target must survive pruning no matter how old it is."""
        return (
            {self._last_good_ckpt}
            if self._last_good_ckpt is not None
            else set()
        )

    def _snapshot_for_write(self) -> Dict[str, Any]:
        """The checkpoint target, through the ``train.snapshot`` chaos
        seam: an armed fault poisons the SNAPSHOT copy (never the live
        carry) — checkpoint-time state corruption, which the non-finite
        write gate (utils/checkpoint.py) must keep invisible to
        discovery."""
        target = self._checkpoint_target()
        try:
            fault_point("train.snapshot")
        except InjectedFault:
            poison = jnp.float32(float("nan"))
            target = dict(target)
            target["params"] = jax.tree_util.tree_map(
                lambda p: p * poison, target["params"]
            )
        return target

    def save_async(self, writer: AsyncCheckpointWriter) -> str:
        """Chunk-boundary checkpoint that never stalls the dispatch
        pipeline: snapshot the state on DEVICE (async copies enqueued
        behind the chunk that produced it — the next chunk's donation
        cannot invalidate them; utils.device_snapshot), then hand the
        snapshot to the writer thread, which ``device_get``s and writes
        atomically while the device keeps training."""
        path = checkpoint_path(self.log_dir, self.num_timesteps)
        on_checkpoint = self.on_checkpoint

        def on_done(p) -> None:
            # Runs on the writer thread AFTER the rename lands — i.e.
            # the file passed the non-finite gate and is durably
            # discoverable: the newest valid rollback target.
            self._last_good_ckpt = Path(p)
            if on_checkpoint is not None:
                on_checkpoint(p)

        writer.submit(
            path,
            device_snapshot(self._snapshot_for_write()),
            on_done=on_done,
        )
        self._vec_steps_since_save = 0
        return str(path)

    def profile_breakdown(self, iters: int = 10) -> Dict[str, float]:
        """Where does the train-iteration time go? Times the full jitted
        iteration and its stages as standalone programs (fractions are
        approximate — standalone stages miss cross-stage fusion, but the
        split is the actionable signal: env vs policy vs update).

        Returns seconds per iteration: ``total``, ``rollout`` (policy
        sampling + env stepping), ``env`` (env stepping alone with fixed
        actions), ``update`` (GAE + minibatch epochs), and derived
        fractions ``frac_*`` of the stage sum.
        """
        import time

        from marl_distributedformation_tpu.env.formation import step_batch

        env_params, ppo = self.env_params, self.ppo
        ts, env_state, obs, key = (
            self.train_state, self.env_state, self.obs, self.key,
        )
        if self.scenario_params is not None:
            # Time the stages through the SAME disturbance stack the total
            # runs through (params close over as trace constants here —
            # fine for a profiling twin), or the breakdown would book the
            # scenario layers' cost to the update phase.
            scenario_params = self.scenario_params
            scenario_step = self._scenario_step_fn

            def env_step_fn(s, v):
                return scenario_step(s, v, scenario_params)
        else:
            env_step_fn = self._env_step_fn or (
                lambda s, v: step_batch(s, v, env_params)
            )
        # Non-donating twin of self._iteration: the training jit donates its
        # state buffers, which repeated timing calls would invalidate.
        iteration_no_donate = jax.jit(self._iteration_core)

        @jax.jit
        def rollout_only(env_state, obs, key):
            return collect_rollout(
                ts.apply_fn, ts.params, env_state, obs, key, env_params,
                ppo.n_steps, env_step_fn=env_step_fn,
            )[2].rewards.sum()

        @jax.jit
        def env_only(env_state, key):
            def body(carry, _):
                state, key = carry
                key, k = jax.random.split(key)
                vel = env_params.max_speed * jax.random.uniform(
                    k, (*state.agents.shape,), minval=-1.0, maxval=1.0
                )
                state, tr = env_step_fn(state, vel)
                return (state, key), tr.reward.sum()

            (_, _), r = jax.lax.scan(
                body, (env_state, key), None, length=ppo.n_steps
            )
            return r.sum()

        @jax.jit
        def _collect(env_state, obs, key):
            return collect_rollout(
                ts.apply_fn, ts.params, env_state, obs, key, env_params,
                ppo.n_steps, env_step_fn=env_step_fn,
            )

        _, last_obs, batch, last_value = _collect(env_state, obs, key)

        @jax.jit
        def update_only(key):
            advantages, returns = compute_gae(
                batch.rewards, batch.values, batch.dones, last_value,
                ppo.gamma, ppo.gae_lambda,
            )
            n = env_params.num_agents
            if self.per_formation:
                row_shape = (n,)
                update_ppo = dataclasses.replace(
                    ppo, batch_size=max(1, ppo.batch_size // n)
                )
            else:
                row_shape = ()
                update_ppo = ppo
            flat = MinibatchData(
                obs=batch.obs.reshape(-1, *row_shape, env_params.obs_dim),
                actions=batch.actions.reshape(
                    -1, *row_shape, env_params.act_dim
                ),
                old_log_probs=batch.log_probs.reshape(-1, *row_shape),
                advantages=advantages.reshape(-1, *row_shape),
                returns=returns.reshape(-1, *row_shape),
            )
            _, m = ppo_update(
                TrainState.create(
                    apply_fn=ts.apply_fn, params=ts.params,
                    tx=ppo.make_optimizer(),
                ),
                flat, key, update_ppo,
            )
            return m["loss"]

        def timed(fn, *args):
            jax.block_until_ready(fn(*args))  # compile + warmup
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / iters

        extra = (
            () if self.scenario_params is None else (self.scenario_params,)
        )
        result = {
            "total": timed(
                lambda: iteration_no_donate(ts, env_state, obs, key, *extra)[
                    4
                ]["loss"]
            ),
            "rollout": timed(rollout_only, env_state, obs, key),
            "env": timed(env_only, env_state, key),
            "update": timed(update_only, key),
        }
        result["policy"] = max(result["rollout"] - result["env"], 0.0)
        stage_sum = result["env"] + result["policy"] + result["update"]
        for k in ("env", "policy", "update"):
            result[f"frac_{k}"] = result[k] / stage_sum if stage_sum else 0.0
        return result

    # ------------------------------------------------------------------
    # Checkpointing (write/read contract: SURVEY.md §5)
    # ------------------------------------------------------------------

    def _checkpoint_target(self) -> Dict[str, Any]:
        target = {
            "policy": self.model.__class__.__name__,
            "params": self.train_state.params,
            "opt_state": self.train_state.opt_state,
            "key": self.key,
            "num_timesteps": self.num_timesteps,
            # Provenance: the rate this state was trained at (sweep member
            # checkpoints record their per-member rate here; resume warns
            # on mismatch).
            "learning_rate": float(self.ppo.learning_rate),
        }
        if not self._multihost:
            # dp-sharded env state is not coordinator-addressable across
            # hosts; multi-host checkpoints carry the learner state only and
            # resume re-resets the environment (on-policy PPO loses nothing
            # but the tail of one rollout).
            target["env_state"] = self.env_state
            target["obs"] = self.obs
        return target

    def save(self) -> Optional[str]:
        """Write a checkpoint; returns its path on the coordinator process
        and None on every other host (the file exists only on the
        coordinator's disk — see utils.save_checkpoint) or when the
        non-finite write gate skipped a poisoned state (audited —
        docs/recovery.md)."""
        path = save_checkpoint(
            self.log_dir, self.num_timesteps, self._snapshot_for_write()
        )
        self._vec_steps_since_save = 0
        if path is not None:
            self._last_good_ckpt = Path(path)
            if self.config.keep_last_n > 0:
                from marl_distributedformation_tpu.utils.checkpoint import (
                    prune_checkpoints,
                )

                prune_checkpoints(
                    self.log_dir,
                    self.config.keep_last_n,
                    protect=self._protected_paths(),
                )
            if self.on_checkpoint is not None:
                self.on_checkpoint(path)
        return str(path) if path is not None else None

    def _learner_template(self) -> Dict[str, Any]:
        return {
            "params": self.train_state.params,
            "opt_state": self.train_state.opt_state,
            "key": self.key,
            "num_timesteps": self.num_timesteps,
        }

    def _try_resume(self) -> None:
        if self._multihost:
            self._try_resume_multihost()
            return
        # Partial restore: a multi-host-written (learner-only) checkpoint
        # resumes fine single-host — env state just starts fresh. A
        # converted SB3 checkpoint (compat/sb3_import.py) carries params
        # only; missing learner pieces (opt_state, key) keep their fresh
        # values — a warm-started fine-tune re-estimates Adam moments
        # within a few iterations. Corrupt/truncated files are
        # quarantined and the walk-back resumes from the newest VALID
        # checkpoint (utils.restore_latest_partial) — a crashed writer
        # costs one checkpoint, never a wedged resume.
        found = restore_latest_partial(
            self.log_dir, self._checkpoint_target()
        )
        if found is None:
            return
        path, restored = found
        # Owning copies BEFORE the donating dispatch sees this state
        # (utils.own_restored: msgpack leaves can alias the checkpoint
        # bytes, and donating an aliased buffer is a use-after-free on
        # the zero-copy CPU backend — observed as garbage params in a
        # resumed fused sweep; the single-run path shares the hazard).
        restored = own_restored(restored)
        self.train_state = self.train_state.replace(
            params=restored["params"],
            opt_state=restored.get("opt_state", self.train_state.opt_state),
        )
        if "key" in restored:
            self.key = restored["key"]
        # num_timesteps stays REQUIRED: every writer (trainer save,
        # sb3_import) records it, so its absence means a truncated or
        # foreign file — silently restarting the counter at 0 would write
        # low-step checkpoints beside high-step ones and reset schedules.
        self.num_timesteps = int(restored["num_timesteps"])
        ckpt_lr = restored.get("learning_rate")
        if ckpt_lr is not None and not jnp.isclose(
            float(ckpt_lr), self.ppo.learning_rate, rtol=1e-6
        ):
            print(
                f"[trainer] WARNING: checkpoint was trained at "
                f"learning_rate={float(ckpt_lr):g} but this run uses "
                f"{self.ppo.learning_rate:g} — pass "
                f"learning_rate={float(ckpt_lr):g} to continue at the "
                "original rate"
            )
        if "env_state" in restored:
            self.env_state = restored["env_state"]
            self.obs = restored["obs"]
        if self._shard_fn is not None:
            # Checkpoints restore as host arrays; re-place them on the
            # mesh or the resumed run silently trains single-device.
            self.train_state, self.env_state, self.obs = self._shard_fn(
                self.train_state, self.env_state, self.obs
            )
        if self._scenario_schedule is not None:
            # Re-enter the schedule where the run left off — every rollout
            # advances num_timesteps by exactly n_steps * num_envs, so the
            # global rollout index is recoverable without extra checkpoint
            # state (restarting at 0 would silently replay the severity
            # ramp from the first stage).
            self._scenario_rollouts = self.num_timesteps // (
                self.ppo.n_steps * self.num_envs
            )
            # The draw counter equals the global rollout index for any
            # run that has not swapped schedules (mid-run swaps are
            # live-process state, not checkpointed — docs/adversarial.md).
            self._scenario_draws = self._scenario_rollouts
            self._resample_scenario_params()
        print(f"[trainer] resumed from {path} at {self.num_timesteps} steps")

    def _try_resume_multihost(self) -> None:
        """Coordinator restores, every host receives the same learner state
        (utils.broadcast_restore); env state stays freshly reset."""
        from marl_distributedformation_tpu.parallel import replicate
        from marl_distributedformation_tpu.utils import broadcast_restore

        restored = broadcast_restore(self.log_dir, self._learner_template())
        if restored is None:
            return
        self.train_state = self.train_state.replace(
            params=restored["params"], opt_state=restored["opt_state"]
        )
        self.key = jnp.asarray(restored["key"])
        self.num_timesteps = int(restored["num_timesteps"])
        self.train_state = replicate(self.train_state, self._shard_fn.mesh)
        print(
            f"[trainer] process {jax.process_index()} resumed (broadcast) "
            f"at {self.num_timesteps} steps"
        )
