"""Fused batched k-NN as a Pallas TPU kernel.

The XLA path (ops/knn.py) materializes the ``(M, N, N)`` pairwise-distance
tensor in HBM and runs ``jax.lax.top_k`` over it — at the BASELINE.json
config-4 scale (M=4096 formations x N=100 agents, every step) that is
~160 MB of HBM round-trip per rollout step plus a sort-based top-k XLA
can't fuse through. This kernel keeps the whole per-formation problem in
VMEM: distance matrix, iterative k-extraction (k unrolled argmin passes —
the standard small-k trick; each pass is one VPU reduction over lanes),
and the neighbor gather via one-hot select, with only the ``(M, k, N)``
results ever touching HBM.

Layout notes (guide: /opt/skills/guides/pallas_guide.md):
- positions are fed struct-of-arrays (x and y as separate ``(M, N)``
  planes) so the lane dimension is the agent axis padded to 128, instead
  of a 2-wide trailing dimension padded 64x;
- outputs are ``(M, k, N)`` (k on the sublane axis) and transposed to the
  public ``(M, N, k)`` layout outside the kernel;
- the grid runs blocks of ``block_m`` formations per program; ``block_m``
  shrinks automatically as N grows so the ``(block_m, Np, Np)``
  intermediates (distance matrix, broadcast planes, selection masks)
  stay within the VMEM budget.

The reference has no neighbor search at all (its interaction graph is the
static ring, reference simulate.py:162-167); this op exists for the new
large-swarm capability and matches ``ops.knn.knn`` bit-for-bit in its
selection and masking semantics (see tests/test_ops_pallas.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from marl_distributedformation_tpu.ops.knn import _SELF_MASK

Array = jax.Array

_LANE = 128
_VMEM_BUDGET = 12 * 1024 * 1024  # bytes; ~6 live (block_m, Np, Np) f32 bufs


def padded_n(n: int) -> int:
    return max(_LANE, ((n + _LANE - 1) // _LANE) * _LANE)


def fits_vmem(n: int) -> bool:
    """True when the kernel's intermediates fit the VMEM budget even at the
    minimum block_m=1 — the dispatch condition for ``impl="auto"``."""
    np_ = padded_n(n)
    return 6 * 4 * np_ * np_ <= _VMEM_BUDGET


def _knn_kernel(k, x_ref, y_ref, vmask_ref, idx_ref, offx_ref, offy_ref,
                dist_ref):
    """One grid step: k-NN for a ``(B, Np)`` block of formations.

    ``vmask`` is 1.0 for live agent columns, 0.0 for padding/invalid; masked
    columns can never be selected. Slots with no real candidate left (all
    remaining distances at ``_SELF_MASK``) degrade to self-loops
    (idx=i, offset=0, dist=0), mirroring ``ops.knn.knn``'s ``valid`` path.
    """
    x = x_ref[:]  # (B, Np)
    y = y_ref[:]
    vm = vmask_ref[:]
    d2 = (x[:, :, None] - x[:, None, :]) ** 2 + (
        y[:, :, None] - y[:, None, :]
    ) ** 2  # (B, Np, Np)
    rows = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 2)
    blocked = (rows == cols) | (vm[:, None, :] < 0.5)
    d2 = jnp.where(blocked, _SELF_MASK, d2)
    row_ids = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)  # (B, Np)
    xb = jnp.broadcast_to(x[:, None, :], d2.shape)
    yb = jnp.broadcast_to(y[:, None, :], d2.shape)
    for j in range(k):  # k is small and static: unrolled argmin passes
        best = jnp.min(d2, axis=2)  # (B, Np)
        amin = jnp.argmin(d2, axis=2).astype(jnp.int32)
        real = best < 0.5 * _SELF_MASK
        onehot = cols == amin[:, :, None]  # exactly one column per row
        nx = jnp.sum(jnp.where(onehot, xb, 0.0), axis=2)
        ny = jnp.sum(jnp.where(onehot, yb, 0.0), axis=2)
        idx_ref[:, j, :] = jnp.where(real, amin, row_ids)
        offx_ref[:, j, :] = jnp.where(real, nx - x, 0.0)
        offy_ref[:, j, :] = jnp.where(real, ny - y, 0.0)
        dist_ref[:, j, :] = jnp.where(
            real, jnp.sqrt(jnp.maximum(best, 0.0)), 0.0
        )
        d2 = jnp.where(onehot, _SELF_MASK, d2)  # exclude from later passes


@functools.partial(jax.jit, static_argnames=("k", "block_m", "interpret"))
def knn_batch_pallas(
    points: Array,
    k: int,
    valid: Optional[Array] = None,
    block_m: Optional[int] = None,
    interpret: bool = False,
) -> Tuple[Array, Array, Array]:
    """Batched k nearest neighbors, fused on-chip.

    Args:
      points: ``(M, N, 2)`` positions for M independent formations.
      k: neighbor count, ``k < N``.
      valid: optional ``(M, N)`` bool mask; invalid points are never
        selected and short rows degrade to self-loops (same contract as
        ``ops.knn.knn``).
      block_m: formations per kernel program. Default: scaled so the
        ~6 live ``(block_m, Np, Np)`` f32 intermediates stay under ~12 MB
        of VMEM (8 formations/program at Np=128, 1 at Np >= 512).
      interpret: run in Pallas interpret mode (CPU tests).

    Returns:
      ``(idx (M, N, k) int32, offsets (M, N, k, 2), dists (M, N, k))``,
      sorted by ascending distance — the ``ops.knn.knn`` layout.
    """
    m, n, d = points.shape
    assert d == 2, f"knn_batch_pallas is 2-D only, got d={d}"
    assert k < n, f"knn needs k < N (k={k}, N={n})"
    n_pad = padded_n(n)
    if not fits_vmem(n):
        raise ValueError(
            f"knn_batch_pallas: N={n} (padded {n_pad}) needs "
            f"~{6 * 4 * n_pad * n_pad >> 20} MB of VMEM intermediates even "
            f"at block_m=1 (budget {_VMEM_BUDGET >> 20} MB); use the XLA "
            "path (knn_batch(..., impl='xla') / EnvParams.knn_impl='xla')"
        )
    if block_m is None:
        # ~6 live (block_m, Np, Np) f32 intermediates (d2, xb, yb, masks)
        # under the VMEM budget.
        block_m = max(1, min(8, _VMEM_BUDGET // (6 * 4) // (n_pad * n_pad)))
    m_pad = ((m + block_m - 1) // block_m) * block_m

    pts = points.astype(jnp.float32)
    x = jnp.pad(pts[..., 0], ((0, m_pad - m), (0, n_pad - n)))
    y = jnp.pad(pts[..., 1], ((0, m_pad - m), (0, n_pad - n)))
    if valid is None:
        vm = jnp.ones((m, n), jnp.float32)
    else:
        vm = valid.astype(jnp.float32)
    vm = jnp.pad(vm, ((0, m_pad - m), (0, n_pad - n)))

    plane = pl.BlockSpec(
        (block_m, n_pad), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    out_plane = pl.BlockSpec(
        (block_m, k, n_pad), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    out_f32 = jax.ShapeDtypeStruct((m_pad, k, n_pad), jnp.float32)
    idx, offx, offy, dist = pl.pallas_call(
        functools.partial(_knn_kernel, k),
        grid=(m_pad // block_m,),
        in_specs=[plane, plane, plane],
        out_specs=[out_plane] * 4,
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, k, n_pad), jnp.int32),
            out_f32,
            out_f32,
            out_f32,
        ],
        interpret=interpret,
    )(x, y, vm)

    idx = jnp.swapaxes(idx[:m, :, :n], 1, 2)  # (M, N, k)
    offsets = jnp.stack(
        [
            jnp.swapaxes(offx[:m, :, :n], 1, 2),
            jnp.swapaxes(offy[:m, :, :n], 1, 2),
        ],
        axis=-1,
    )
    dists = jnp.swapaxes(dist[:m, :, :n], 1, 2)
    return idx, offsets, dists
